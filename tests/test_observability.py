"""Unified observability (ISSUE 15): deterministic request tracing,
failure flight recorder, and the one metrics registry.

The contract under test is the house discipline itself — counter
clocks, never wall clocks — so the assertions are BYTE equality:

- same seed + same fault plan (after a reset) ⇒ byte-identical
  ``Tracer.to_json()`` AND byte-identical flight-recorder JSON, across
  reruns — including the acceptance drill: a 2-replica routed run
  under a ``replica.health`` death plan whose postmortem names the
  dead replica, the requeued requests, and their reset/re-dispatch
  events;
- tracing DISABLED ⇒ zero spans and engine streams bit-identical to
  the traced run (observability never perturbs streams);
- tracing adds ZERO compiled programs (compile-ledger delta);
- every declared fault site fires its registered ``fault.<site>``
  event (the matrix over ``faults.SITES``), and the O001 ``obs_check``
  pass red-teams the coverage cross-check.

Tiny single-purpose engines (1-layer LM, single-device mesh) keep the
matrix cheap; the invariants live in event streams and counters, not
model size."""

import json
import os
import tempfile

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.analysis import check_observability, get_ledger
from mxtpu.models.transformer import TransformerLM, \
    transformer_lm_sharding_rules
from mxtpu.observability import (EVENT_TYPES, MetricsRegistry,
                                 export_chrome_trace, flight_recording,
                                 get_flight, get_registry, get_tracer,
                                 tracing)
from mxtpu.parallel import ContinuousBatchingEngine, \
    PagedContinuousBatchingEngine
from mxtpu.parallel.mesh import DeviceMesh
from mxtpu.resilience import fault_plan
from mxtpu.resilience.faults import SITES, inject


@pytest.fixture(scope="module")
def micro_lm():
    mx.random.seed(7)
    lm = TransformerLM(32, units=16, hidden_size=32, num_layers=1,
                       num_heads=2, num_kv_heads=2)
    lm.initialize()
    return lm


@pytest.fixture(scope="module")
def mesh():
    return DeviceMesh(dp=1)


@pytest.fixture(scope="module")
def rules():
    return transformer_lm_sharding_rules()


def _paged_engine(lm, mesh, rules, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_length", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return PagedContinuousBatchingEngine(lm, mesh, rules, **kw)


def _prompts():
    rng = np.random.RandomState(0)
    shared = rng.randint(0, 32, (1, 11))
    pa = np.concatenate([shared, rng.randint(0, 32, (1, 6))], axis=1)
    pb = np.concatenate([shared, rng.randint(0, 32, (1, 4))], axis=1)
    return pa, pb


# ---------------------------------------------------------------- tracer


def test_tracer_off_by_default_emit_is_noop():
    tr = get_tracer()
    assert not tr.enabled
    assert tr.emit("engine.decode", rid="eng:0", pos=1) is None
    assert tr.events() == []


def test_emit_unknown_event_type_raises():
    with tracing() as tr:
        with pytest.raises(ValueError, match="unregistered trace event"):
            tr.emit("engine.decoed", rid="eng:0")


def test_tracing_context_restores_prior_state():
    assert not get_tracer().enabled
    with tracing() as tr:
        assert tr.enabled
        with tracing():         # nested: stays enabled afterwards
            pass
        assert tr.enabled
    assert not get_tracer().enabled


def test_span_pairs_and_alias_resolution():
    with tracing() as tr:
        tr.alias("eng:0", "gw:5")
        with tr.span("engine.iteration", tag="eng"):
            tr.emit("engine.decode", rid="eng:0", pos=3)
        evs = tr.events()
        assert [e.phase for e in evs] == ["B", "I", "E"]
        assert [e.tick for e in evs] == [1, 2, 3]
        # the aliased rid resolved at record time
        assert evs[1].rid == "gw:5"
        assert tr.timeline("gw:5") == [evs[1]]
        assert tr.timeline("eng:0") == [evs[1]]   # query resolves too
        assert tr.span_count() == 1


def test_fault_site_event_matrix():
    """Every DECLARED site's firing lands in the trace under its
    registered ``fault.<site>`` type — raise and delay actions alike
    (the satellite matrix over ``faults.SITES``)."""
    for site in SITES:
        etype = "fault." + site
        assert etype in EVENT_TYPES     # the O001 invariant, directly
        with tracing() as tr:
            with fault_plan("%s@1:raise" % site):
                with pytest.raises(Exception):
                    inject(site, key=1)
            evs = tr.events(types=etype)
            assert len(evs) == 1, site
            assert evs[0].fields["site"] == site
            assert evs[0].fields["action"] == "raise"
            assert evs[0].fields["key"] == "1"
    # delay action, one representative site (no real sleep)
    with tracing() as tr:
        with fault_plan("serving.step@1:delay=0.5", sleep=lambda s: None):
            inject("serving.step", key=9)
        (ev,) = tr.events(types="fault.serving.step")
        assert ev.fields["action"] == "delay"


def test_fault_event_unregistered_site_downgrades():
    with tracing() as tr:
        with fault_plan("tests.private.site@1:raise"):
            with pytest.raises(Exception):
                inject("tests.private.site")
        (ev,) = tr.events(types="fault.unregistered")
        assert ev.fields["site"] == "tests.private.site"


# -------------------------------------------------------- flight recorder


def test_flight_ring_buffer_bounds():
    with tracing(), flight_recording(buffer=4) as fl:
        tr = get_tracer()
        for i in range(10):
            tr.emit("engine.decode", rid="eng:0", pos=i)
        tl = fl.timeline("eng:0")
        assert len(tl) == 4
        assert [e.fields["pos"] for e in tl] == [6, 7, 8, 9]


def test_flight_failure_inactive_is_noop():
    fl = get_flight()
    assert not fl.active
    assert fl.failure("quarantine", rids=("eng:0",)) is None


def test_flight_recording_restores_ambient_state():
    """A scoped flight_recording() inside a process running with the
    ambient recorder (MXTPU_FLIGHT_BUFFER) must restore BOTH the
    attached state and the buffer size on exit — not switch the
    always-on postmortem capture off for the rest of the process."""
    fl = get_flight()
    assert not fl.active
    prev_buffer = fl.buffer
    try:
        fl.enable(buffer=96, reset=True)      # simulate ambient
        with flight_recording(buffer=8) as scoped:
            assert scoped is fl and fl.buffer == 8
        assert fl.active and fl.buffer == 96
    finally:
        fl.disable()
        fl._buffer = prev_buffer
    # and when it was off, it stays off with its size untouched
    fl2_buffer = fl.buffer
    with flight_recording(buffer=8):
        pass
    assert not fl.active and fl.buffer == fl2_buffer


def test_ambient_flight_buffer_import_order(tmp_path):
    """MXTPU_FLIGHT_BUFFER arms the recorder at import regardless of
    which package is imported first: the module-level construction
    takes its counters baseline without importing mxtpu.resilience
    (which imports this module back — the circular-import regression),
    and a later failure still reads a correct counters delta."""
    import subprocess
    import sys as _sys
    code = (
        "from mxtpu.observability import get_flight\n"
        "fl = get_flight()\n"
        "assert fl.active and fl.buffer == 48, (fl.active, fl.buffer)\n"
        "from mxtpu.resilience.counters import bump\n"
        "bump('probe_counter', 3)\n"
        "pm = fl.failure('shed', context='bootstrap-probe')\n"
        "assert pm.counters == {'probe_counter': 3}, pm.counters\n"
    )
    env = dict(os.environ, MXTPU_FLIGHT_BUFFER="48",
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([_sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]


def test_alias_map_bounded():
    """One alias lands per submitted request; in the always-on posture
    (ambient flight recorder, tracer never reset) the map must stay
    bounded — oldest-registered evicted past MAX_ALIASES."""
    from mxtpu.observability.trace import MAX_ALIASES
    with tracing() as tr:
        for i in range(MAX_ALIASES + 7):
            tr.alias("eng:%d" % i, "gw:%d" % i)
        assert len(tr._alias) == MAX_ALIASES
        assert tr.resolve("eng:0") == "eng:0"           # evicted
        newest = MAX_ALIASES + 6
        assert tr.resolve("eng:%d" % newest) == "gw:%d" % newest
        # re-registering an existing child never evicts
        tr.alias("eng:%d" % newest, "gw:%d" % newest)
        assert len(tr._alias) == MAX_ALIASES


def test_ckpt_corruption_flight_postmortem(tmp_path):
    from mxtpu.resilience import checkpoint as ckpt

    with flight_recording(buffer=16) as fl:
        cs = ckpt.CheckpointSet(str(tmp_path), keep=3)
        cs.save(0, b"good-0")
        cs.save(1, b"good-1")
        buf = bytearray(open(cs.path(1), "rb").read())
        buf[0] ^= 0xFF
        open(cs.path(1), "wb").write(bytes(buf))
        assert cs.latest_verified() == (0, b"good-0")
        (pm,) = fl.postmortems
        assert pm.kind == "ckpt_corruption"
        assert pm.context["step"] == 1
        assert pm.context["file"] == os.path.basename(cs.path(1))
        # counters delta carries the detection
        assert pm.counters.get("ckpt_corruptions") == 1


# --------------------------------------------------------- engine traces


def test_timeline_covers_request_path(micro_lm, mesh, rules):
    """One shared-prefix pair on the paged engine: the second request's
    timeline carries admission → prefix hit → COW → prefill chunk →
    decode → finish, in tick order."""
    pa, pb = _prompts()
    eng = _paged_engine(micro_lm, mesh, rules)
    with tracing() as tr:
        eng.submit(nd.array(pa, dtype="int32"), 3)
        for _ in range(3):
            eng.step()          # register A's pages
        rb = eng.submit(nd.array(pb, dtype="int32"), 3)
        eng.run()
        tl = tr.timeline("eng:%d" % rb)
        kinds = [e.etype for e in tl]
        for k in ("engine.admit", "engine.prefix_hit", "engine.cow",
                  "engine.prefill_chunk", "engine.decode",
                  "engine.finish"):
            assert k in kinds, kinds
        assert [e.tick for e in tl] == sorted(e.tick for e in tl)
        hit = next(e for e in tl if e.etype == "engine.prefix_hit")
        # 8 tokens from the full shared page + 3 via the COW donor edge
        assert hit.fields["tokens"] == 11
        assert hit.fields["pages"] == 1
        fin = next(e for e in tl if e.etype == "engine.finish")
        assert fin.fields["status"] == "ok"
        # spans recorded around every scheduler iteration
        assert tr.span_count() > 0


def test_trace_and_flight_deterministic_bytes(micro_lm, mesh, rules):
    """Same seed + same fault plan ⇒ byte-identical trace JSON and
    flight JSON across reruns (the tick clock, alias map, and counter
    baselines all reset with the contexts)."""
    pa, pb = _prompts()

    def run_once():
        eng = _paged_engine(micro_lm, mesh, rules)
        with tracing() as tr, flight_recording(64) as fl:
            with fault_plan("serving.step@3:raise=RuntimeError(boom)"):
                eng.submit(nd.array(pa, dtype="int32"), 3, seed=5,
                           temperature=0.7)
                eng.submit(nd.array(pb, dtype="int32"), 3, retries=1)
                eng.run()
            return tr.to_json(), fl.to_json()

    t1, f1 = run_once()
    t2, f2 = run_once()
    assert t1 == t2
    assert f1 == f2
    rec = json.loads(f1)
    assert any(p["kind"] == "quarantine" for p in rec["postmortems"])


def test_tracer_off_streams_bit_exact_and_zero_extra_programs(
        micro_lm, mesh, rules):
    """The no-perturbation acceptance: the SAME engine serves the same
    workload untraced and traced — outputs bit-identical, zero new
    compiled programs while traced, zero events while untraced."""
    pa, pb = _prompts()
    eng = _paged_engine(micro_lm, mesh, rules)

    def run_once():
        r0 = eng.submit(nd.array(pa, dtype="int32"), 4, seed=3,
                        temperature=0.8)
        r1 = eng.submit(nd.array(pb, dtype="int32"), 4)
        out = eng.run()
        return out[r0].asnumpy(), out[r1].asnumpy()

    run_once()                          # compile warmup
    get_tracer().reset()                # drop prior tests' events
    base = run_once()                   # tracer OFF
    assert get_tracer().events() == []
    led = get_ledger()
    seq = led.sequence()
    with tracing() as tr:
        traced = run_once()             # tracer ON, same engine
        assert len(tr.events()) > 0
    assert len(led.misses_after(seq, sites=("serving.*",))) == 0
    assert np.array_equal(base[0], traced[0])
    assert np.array_equal(base[1], traced[1])


def test_chrome_export_golden_shape(micro_lm, mesh, rules):
    pa, _ = _prompts()
    eng = _paged_engine(micro_lm, mesh, rules)
    from mxtpu import profiler
    with tracing() as tr:
        eng.submit(nd.array(pa, dtype="int32"), 2)
        eng.run()
        profiler.Marker("golden_marker").mark()
        text = export_chrome_trace()
    doc = json.loads(text)
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    for ev in evs:
        assert set(("name", "ph", "ts", "pid", "tid")) <= set(ev)
        assert ev["ph"] in ("B", "E", "i", "X", "C")
        if ev["ph"] == "i":
            assert ev["s"] == "t"
    # span begin/end balanced per (name, tid)
    opens = {}
    for ev in evs:
        key = (ev["name"], ev["tid"])
        if ev["ph"] == "B":
            opens[key] = opens.get(key, 0) + 1
        elif ev["ph"] == "E":
            opens[key] -= 1
    assert all(v == 0 for v in opens.values()), opens
    # the profiler Marker rode the same writer
    assert any(e["name"] == "golden_marker" for e in evs)
    # file form writes the identical bytes
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "t.json")
        export_chrome_trace(p, tracer=tr)
        assert json.loads(open(p).read())["traceEvents"] == evs


# ----------------------------------------------- acceptance: replica death


def test_replica_death_postmortem_deterministic_and_complete(
        micro_lm, mesh, rules):
    """ISSUE 15 acceptance: a faulted 2-replica routed run (1-in-N
    ``replica.health`` death plan, probation revival — the
    ``_bench_router`` shape) produces a flight postmortem that is
    byte-identical across reruns, names the dead replica and the
    requeued requests, whose timelines carry the requeue ("reset") and
    re-dispatch events — and tracing adds ZERO compiled programs vs
    the identical untraced run."""
    from mxtpu.serving import Gateway, replica_pool

    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 32, (1, 9)) for _ in range(3)]
    led = get_ledger()

    def build():
        return Gateway(replica_pool(
            lambda i: _paged_engine(micro_lm, mesh, rules), n=2),
            fail_threshold=1, revive_after_ticks=8,
            hedge_fraction=None)

    def drive(gw):
        rids = [gw.submit(nd.array(p, dtype="int32"), 4, seed=i,
                          temperature=0.6)
                for i, p in enumerate(prompts)]
        return rids, gw.run()

    plan = "replica.health#r0@3:raise=OSError(drill)"

    # arm 0: untraced (the compile-count and stream reference)
    seq = led.sequence()
    gw0 = build()
    with fault_plan(plan):
        rids0, res0 = drive(gw0)
    untraced = len(led.misses_after(seq, sites=("serving.*",)))

    def run_traced():
        gw = build()
        seq = led.sequence()
        with tracing() as tr, flight_recording(128) as fl:
            with fault_plan(plan):
                rids, res = drive(gw)
            compiles = len(led.misses_after(seq, sites=("serving.*",)))
            pms = [p for p in fl.postmortems
                   if p.kind == "replica_death"]
            assert len(pms) == 1
            pm = pms[0]
            record = fl.postmortem_record(pm)
            return (gw, rids, res, pm, record, fl.to_json(),
                    compiles)

    gw1, rids1, res1, pm, record, fjson1, compiles1 = run_traced()
    # deaths happened and streams survived identical to the untraced arm
    assert gw1.stats["supervisor"]["deaths"] == 1
    for ra, rb in zip(rids0, rids1):
        assert np.array_equal(res0[ra].asnumpy(), res1[rb].asnumpy())
    # tracing compiled NOTHING beyond what the untraced arm compiled
    assert compiles1 == untraced

    # the postmortem names the dead replica and the drained requests
    assert pm.context["replica"] == "r0"
    assert len(pm.rids) >= 1
    for rid in pm.rids:
        tl = record["requests"][rid]
        kinds = [e["type"] for e in tl]
        # the death tick splits history from recovery: the requeue
        # (stream reset) and the re-dispatch both present
        assert "gateway.requeue" in kinds
        redispatch = [e for e in tl
                      if e["type"] == "gateway.dispatch"
                      and e["tick"] > pm.tick]
        assert redispatch, kinds

    # rerun: byte-identical flight record
    _, _, _, _, _, fjson2, _ = run_traced()
    assert fjson1 == fjson2


# --------------------------------------------------------------- guardian


def test_guardian_events_and_rollback_postmortem(tmp_path):
    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import SPMDTrainer
    from mxtpu.resilience.guardian import Guardian

    mx.random.seed(3)
    net = nn.Dense(4, in_units=8, prefix="obs_g_")
    net.initialize()
    tr_ = SPMDTrainer(net, gluon.loss.L2Loss(), "sgd", DeviceMesh(dp=1),
                      optimizer_params={"learning_rate": 1e-2},
                      guard=True)
    R = np.random.RandomState(0)
    data = [(R.randn(4, 8).astype(np.float32),
             R.randn(4, 4).astype(np.float32)) for _ in range(6)]

    def data_fn(step):
        d, l = data[step % len(data)]
        return mx.nd.array(d), mx.nd.array(l)

    g = Guardian(str(tmp_path), max_skips=1, checkpoint_every=100)
    with tracing() as trc, flight_recording(64) as fl:
        with fault_plan("guardian.check#3@1:raise"):
            g.run(tr_, data_fn, num_steps=6)
        kinds = [e.etype for e in trc.timeline("train")]
        assert "guardian.checkpoint" in kinds    # the baseline save
        assert "guardian.rollback" in kinds
        assert "fault.guardian.check" in [e.etype for e in trc.events()]
        pms = [p for p in fl.postmortems if p.kind == "guardian_rollback"]
        assert len(pms) == 1
        assert pms[0].context["restored_step"] == 0
        assert pms[0].counters.get("guardian_rollbacks") == 1


# ------------------------------------------------------- metrics registry


def test_registry_flatten_snapshot_and_delta():
    reg = MetricsRegistry()
    reg.register_source("a", lambda: {"x": 1, "nested": {"y": 2.5,
                                                         "flag": True},
                                      "skip": "str",
                                      "bad": {3: 4}})
    snap = reg.snapshot()
    assert snap == {"a.x": 1, "a.nested.y": 2.5, "a.nested.flag": 1}
    reg.register_source("a", lambda: {"x": 4, "nested": {"y": 2.5}},
                        replace=True)
    assert reg.delta(snap) == {"a.x": 3}
    assert reg.delta(snap, include_zero=True)["a.nested.y"] == 0


def test_registry_register_stats_and_prometheus(micro_lm, mesh, rules):
    pa, _ = _prompts()
    eng = _paged_engine(micro_lm, mesh, rules)
    reg = MetricsRegistry()
    reg.register_stats("engine0", eng)
    before = reg.snapshot()
    eng.submit(nd.array(pa, dtype="int32"), 3)
    eng.run()
    d = reg.delta(before)
    # 2 decode-step tokens: the first of the 3 emitted tokens samples
    # at prefill completion (generated_tokens counts decode steps)
    assert d["engine0.generated_tokens"] == 2
    assert d["engine0.steps"] > 0
    prom = reg.to_prometheus()
    assert "# TYPE mxtpu_engine0_generated_tokens gauge" in prom
    assert "mxtpu_engine0_generated_tokens 2" in prom
    parsed = json.loads(reg.to_json())
    assert parsed["engine0.generated_tokens"] == 2
    reg.unregister("engine0")
    assert reg.sources() == []


def test_registry_source_errors_and_misuse():
    reg = MetricsRegistry()
    reg.register_source("boom", lambda: 1 / 0)
    assert reg.snapshot() == {"boom.source_error": 1}
    with pytest.raises(ValueError, match="already registered"):
        reg.register_source("boom", dict)
    with pytest.raises(TypeError):
        reg.register_source("x", 42)
    with pytest.raises(TypeError):
        reg.register_stats("y", object())
    with pytest.raises(KeyError):
        reg.snapshot(sources=("nope",))


def test_process_registry_builtin_sources():
    reg = get_registry()
    assert {"resilience", "compile_ledger", "engine_bulk", "profiler",
            "tracer", "flight"} <= set(reg.sources())
    snap = reg.snapshot(sources=("resilience", "tracer", "flight"))
    assert "resilience.quarantined_slots" in snap
    assert "tracer.events" in snap
    assert "flight.postmortems" in snap
    # ledger sites flatten to <site>.programs (the O001 key shape)
    led_snap = reg.snapshot(sources=("compile_ledger",))
    for site in get_ledger().sites():
        assert "compile_ledger.%s.programs" % site in led_snap


# ----------------------------------------------- stats key normalization


def test_engine_and_gateway_stats_key_normalization(micro_lm, mesh,
                                                    rules):
    """The deprecated alias spellings are gone for good: every stats
    surface exposes ONLY the canonical ``*_requests``/``*_blocks``
    names, so no first-party reader can silently keep leaning on a
    removed key."""
    from mxtpu.serving import Gateway, replica_pool

    eng = ContinuousBatchingEngine(micro_lm, mesh, rules, num_slots=2,
                                   max_length=32)
    st = eng.stats
    for old, new in (("tokens_generated", "generated_tokens"),
                     ("quarantined", "quarantined_requests"),
                     ("retries", "retried_requests"),
                     ("deadline_evictions", "expired_requests"),
                     ("shed", "shed_requests")):
        assert old not in st, old
        assert new in st, new
    pst = _paged_engine(micro_lm, mesh, rules).stats
    for old, new in (("prefix_hits", "prefix_hit_requests"),
                     ("cow_copies", "cow_copied_blocks"),
                     ("swap_ins", "swapped_in_blocks"),
                     ("swap_outs", "swapped_out_blocks"),
                     ("deferred_swap_ins", "deferred_swap_in_requests"),
                     ("session_hits", "session_hit_requests")):
        assert old not in pst, old
        assert new in pst, new
    gw = Gateway(replica_pool(
        lambda i: _paged_engine(micro_lm, mesh, rules), n=1))
    gst = gw.stats
    for old, new in (("qos_sheds", "qos_shed_requests"),
                     ("engine_sheds", "engine_shed_requests"),
                     ("hedges", "hedged_requests")):
        assert old not in gst, old
        assert new in gst, new


# ----------------------------------------------------------- obs_check


def test_obs_check_clean_on_live_state():
    rep = check_observability()
    assert len(rep.filter(code="O001")) == 0, str(rep)
    assert rep.ok


def test_obs_check_red_team_unregistered_site():
    rep = check_observability(sites=("made.up.site",))
    o1 = rep.filter(code="O001").diagnostics
    assert len(o1) == 1
    assert o1[0].subject == "made.up.site"
    assert "fault.made.up.site" in o1[0].message


def test_obs_check_red_team_registry_losses():
    # a registry stripped of the compile_ledger source entirely
    rep = check_observability(registry=MetricsRegistry())
    assert any(d.subject == "compile_ledger"
               for d in rep.filter(code="O001"))
    # a filtering replacement that drops a recorded site
    led = get_ledger()
    if led.sites():
        lost = led.sites()[0]
        reg = MetricsRegistry()
        reg.register_source(
            "compile_ledger",
            lambda: {s: {"programs": 1}
                     for s in led.sites() if s != lost})
        rep = check_observability(registry=reg)
        assert any(d.subject == lost for d in rep.filter(code="O001"))


def test_obs_check_registered_in_cli_gate():
    from mxtpu.analysis import list_passes
    from mxtpu.analysis.__main__ import _SELF_APPLY

    assert "obs_check" in list_passes()
    assert "obs_check" in _SELF_APPLY


# ------------------------------------------------------- profiler parity


def test_profiler_set_config_warns_on_unknown_key():
    from mxtpu import profiler

    with pytest.warns(UserWarning, match="profile_al"):
        profiler.set_config(profile_al=True)
    with pytest.warns(UserWarning, match="did you mean"):
        profiler.set_config(agregate_stats=True)
    # known keys configure silently (and typos did NOT land)
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        profiler.set_config(aggregate_stats=True)
    assert "profile_al" not in profiler._config


def test_profiler_counters_markers_serve_through_registry():
    from mxtpu import profiler

    c = profiler.Counter("obs_test_counter", value=2)
    c.increment(3)
    assert profiler.counter_values()["obs_test_counter"] == 5
    snap = get_registry().snapshot(sources=("profiler",))
    assert snap["profiler.obs_test_counter"] == 5
    # dumps() aggregates through the registry + the tracer channel
    with profiler.Event("obs_test_scope"):
        pass
    text = profiler.dumps(reset=True)
    assert "obs_test_counter" in text
    assert "obs_test_scope" in text
    assert get_tracer().profiler_events() == []     # reset consumed them
    # with tracing active, Counter/Marker land in the structured trace
    with tracing() as tr:
        c.increment()
        profiler.Marker("obs_test_marker").mark()
        types = [e.etype for e in tr.events()]
        assert "profiler.counter" in types
        assert "profiler.marker" in types
