"""kernel_check (ISSUE 12): static TPU tile-geometry / VMEM-budget /
grid-safety analysis for Pallas kernels.

Three claims pinned here:

1. **Self-application is the merge gate** — the shipped kernels
   (flash_attention fwd+bwd, conv_bwd, paged_attention) at their REAL
   TPU serving/training geometries (fp32 and int8, decode and W-wide
   verify) report ZERO ERROR, so every ROADMAP-item-2 kernel lands
   behind an asserted-on-CPU geometry verdict.
2. **Every K code fires exactly where expected** — a red-team fixture
   bank of deliberately broken specs, one per rule.
3. **The VMEM estimator prices the real call** — kernel_vmem_estimate
   agrees with the interpret-mode pallas_call's actual grid/block/
   scratch shapes on the paged-attention kernel (captured from the real
   invocation), and the runtime guard mirrors the static rules.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from mxtpu.analysis import (BlockOperand, KernelSpec, ScalarPrefetch,
                            ScratchOperand, Severity, check_kernels,
                            default_kernel_specs, kernel_vmem_estimate,
                            list_passes, run_pass, sublane_tile)
from mxtpu.ops.pallas import paged_attention as pa


def _codes(rep):
    return sorted({d.code for d in rep})


def _spec(block, array, dtype="float32", kind="in", grid=(4,),
          imap=None, **kw):
    imap = imap if imap is not None else (lambda *a: (0,) * len(block))
    return KernelSpec(
        "fixture", grid,
        [BlockOperand("x", kind, block, array, dtype, imap)], **kw)


# ------------------------------------------------ 1. self-application

def test_shipped_kernels_pass_clean_at_tpu_geometries():
    """The merge gate: flash fwd+bwd (fp32 + bf16), conv_bwd, and
    paged_attention (fp32 bs=16 + int8 bs=32, W=1 decode + W=8 verify)
    — zero ERROR, zero WARNING, one M007 pricing INFO per spec."""
    specs = default_kernel_specs()
    names = " ".join(s.name for s in specs)
    assert "flash_attention.fwd" in names
    assert "flash_attention.bwd_dq" in names
    assert "flash_attention.bwd_dkv" in names
    assert "conv_bwd" in names
    assert "paged_attention[int8,W=8" in names
    assert "paged_attention[float32,W=1" in names
    rep = check_kernels(specs)
    assert rep.ok, "TPU geometry regression:\n%s" % rep
    assert not rep.warnings, "unexpected warnings:\n%s" % rep
    assert len(rep.filter(code="M007")) == len(specs)


def test_kernel_check_is_a_registered_pass():
    assert "kernel_check" in list_passes()
    rep = run_pass("kernel_check")
    assert rep.ok


def test_int8_sublane_floor_is_enforced_not_prose():
    """The ROADMAP "block_size >= 32 for int8" rule: the same paged
    geometry that passes at bs=32 fails K002 at bs=16 (int8 sublane
    tile is 32), while fp32 accepts bs=16 (sublane 8)."""
    bad = pa.kernel_spec(B=4, KV=2, rep=4, W=1, D=128, block_size=16,
                         max_length=256, cache_dtype="int8")
    rep = check_kernels([bad])
    hit = rep.filter(code="K002", min_severity=Severity.ERROR)
    assert {d.subject for d in hit} == {
        "%s.pool_k" % bad.name, "%s.pool_v" % bad.name}
    ok = pa.kernel_spec(B=4, KV=2, rep=4, W=1, D=128, block_size=16,
                        max_length=256, cache_dtype="float32")
    assert check_kernels([ok]).ok


# ------------------------------------------- 2. red-team fixture bank

def test_k001_last_dim_not_lane_aligned():
    s = _spec((1, 8, 64), (4, 8, 256), imap=lambda i: (i, 0, 0))
    rep = check_kernels([s])
    hit = rep.filter(code="K001")
    assert len(hit) == 1 and hit.diagnostics[0].severity == Severity.ERROR
    assert hit.diagnostics[0].subject == "fixture.x"
    assert _codes(rep) == ["K001", "M007"]


def test_k001_full_axis_block_is_exempt():
    """A block covering the whole (sub-128) axis pads a partial lane
    tile — legal; only CHOSEN non-aligned tilings are defects."""
    s = _spec((1, 8, 64), (4, 8, 64), imap=lambda i: (i, 0, 0))
    assert check_kernels([s]).ok


def test_k002_sublane_tile_per_dtype():
    for dtype, sub in (("float32", 8), ("bfloat16", 16), ("int8", 32)):
        assert sublane_tile(dtype) == sub
        bad = _spec((1, sub // 2, 128), (4, 4 * sub, 128), dtype=dtype,
                    imap=lambda i: (i, 0, 0))
        rep = check_kernels([bad])
        assert _codes(rep) == ["K002", "M007"], dtype
        ok = _spec((1, sub, 128), (4, 4 * sub, 128), dtype=dtype,
                   imap=lambda i: (i, 0, 0))
        assert check_kernels([ok]).ok, dtype


def test_k002_size_one_sublane_is_exempt():
    """(1, 128) windows — the lse/scale-row pattern — lower as a
    single-sublane broadcast; not a defect."""
    s = _spec((1, 128), (32, 1024), imap=lambda b: (b, 0))
    assert check_kernels([s]).ok


def test_k003_vmem_budget_and_configurability():
    big = _spec((1, 8192, 1024), (2, 8192, 1024), grid=(2,),
                imap=lambda i: (i, 0, 0))
    rep = check_kernels([big])   # 2 x 32MiB > 16MiB default
    hit = rep.filter(code="K003")
    assert len(hit) == 1 and not rep.ok
    assert hit.diagnostics[0].details["budget_bytes"] == 16 * 2**20
    # the same spec passes a raised budget; a small one fails anything
    assert check_kernels([big], vmem_budget="128MiB").ok
    tiny = _spec((1, 8, 128), (2, 8, 128), imap=lambda i: (i, 0, 0))
    assert not check_kernels([tiny], vmem_budget="1KiB").ok


def test_k004_block_table_entry_past_pool_extent():
    """The null-page-0 convention is modeled: a legal ragged table
    passes; corrupting ONE live entry to the pool size fires K004 with
    the offending grid index."""
    ok = pa.kernel_spec(B=3, KV=2, rep=2, W=1, D=128, block_size=8,
                        max_length=64, num_blocks=8)
    assert check_kernels([ok]).ok
    tables, pos = pa._model_tables(3, 8, 8, 8, 1, 64)
    tables[1, 0] = 8                      # == N: one page past the pool
    bad = pa.kernel_spec(B=3, KV=2, rep=2, W=1, D=128, block_size=8,
                         max_length=64, num_blocks=8, tables=tables,
                         pos=pos)
    rep = check_kernels([bad])
    hit = rep.filter(code="K004")
    assert {d.subject for d in hit} == {
        "%s.pool_k" % bad.name, "%s.pool_v" % bad.name}
    for d in hit:
        assert d.details["grid_index"][0] == 1   # slot 1's walk
        assert d.details["extent"] == 8
    # the corrupt value also trips the declared-range validation
    assert len(rep.filter(code="K005")) >= 1
    # overrides apply INDEPENDENTLY: auditing a real engine's corrupt
    # table with pos omitted must still evaluate THAT table, never
    # fall back to clean model tables
    bad2 = pa.kernel_spec(B=3, KV=2, rep=2, W=1, D=128, block_size=8,
                          max_length=64, num_blocks=8, tables=tables)
    assert not check_kernels([bad2]).ok


def test_k004_affine_map_overruns_unpadded_array():
    # grid covers 6 blocks of 128 but the array holds only 512 rows
    s = _spec((128, 128), (512, 128), grid=(6,),
              imap=lambda i: (i, 0))
    rep = check_kernels([s])
    hit = rep.filter(code="K004")
    assert len(hit) == 1
    assert hit.diagnostics[0].details["block_index"] == 4
    assert not rep.ok


def test_k004_fires_on_sampled_oversize_grids():
    """Past max_grid_points the sweep samples large axes at their
    extremes — an overrun at the grid corner is still caught, and the
    partial sweep is announced as a K008 INFO so a clean verdict can
    never silently mean 'mostly unchecked'."""
    s = _spec((8, 128), (1024, 128), grid=(1000, 1000),
              imap=lambda i, j: (i + j, 0))
    rep = check_kernels([s], max_grid_points=1024)
    hit = rep.filter(code="K004")
    assert len(hit) == 1
    assert "sampled" in hit.diagnostics[0].message
    k8 = rep.filter(code="K008")
    assert len(k8) == 1
    assert k8.diagnostics[0].details["grid_points"] == 1000 * 1000
    # small (table-sized) axes stay FULLY swept even when sampling: a
    # corrupt entry on an unsampled-looking slot axis is still caught
    s2 = _spec((8, 128), (1024, 128), grid=(64, 1000),
               imap=lambda b, j: (jnp.where(b == 37, 200, 0), 0))
    rep2 = check_kernels([s2], max_grid_points=1024)
    assert len(rep2.filter(code="K004")) == 1
    # a fully-swept grid never emits K008
    assert not check_kernels(
        [pa.kernel_spec(B=4, KV=2, rep=2, W=1, D=128, block_size=8,
                        max_length=64, num_blocks=8)]).filter(
        code="K008").diagnostics


def test_grid_sampling_enforces_the_point_cap():
    """The sweep cap is a hard memory bound: many small (fully-swept)
    axes whose product still exceeds max_grid_points fall back to edge
    sampling everywhere instead of materializing the product."""
    from mxtpu.analysis.kernel_check import _grid_points

    coords, sampled = _grid_points((64, 64, 64, 64), 1000)
    assert sampled
    assert len(coords[0]) <= 1000
    # a single oversize axis still keeps its neighbours full
    coords, sampled = _grid_points((8, 1000), 1024)
    assert sampled and len(coords[0]) == 8 * 5


def test_block_operand_rejects_rank_mismatch():
    """Geometry and extent rules align block dims with array dims
    positionally — a rank mismatch must be rejected up front, not
    checked against the wrong extents (failing open on the tail)."""
    with pytest.raises(ValueError, match="same rank"):
        BlockOperand("x", "in", (1, 8, 128), (4, 2, 8, 128), "float32")


def test_k004_error_even_in_interpret_mode():
    """Out-of-extent indexing is wrong on CPU too — interpret never
    downgrades K004."""
    s = _spec((128, 128), (512, 128), grid=(6,),
              imap=lambda i: (i, 0), interpret=True)
    rep = check_kernels([s])
    assert len(rep.filter(code="K004", min_severity=Severity.ERROR)) == 1


def test_k005_prefetch_dtype_and_range_hygiene():
    base = dict(block=(1, 8, 128), array=(4, 8, 128), grid=(4,))
    s = KernelSpec("fixture", (4,),
                   [BlockOperand("x", "in", base["block"], base["array"],
                                 "float32", lambda i, t, u: (i, 0, 0))],
                   prefetch=[
                       ScalarPrefetch("t", np.zeros(4, np.int64)),
                       ScalarPrefetch("u", np.array([9], np.int32),
                                      valid_range=(0, 4))])
    rep = check_kernels([s])
    hit = rep.filter(code="K005")
    # t: wrong dtype AND undeclared range; u: value 9 outside [0, 4)
    t_msgs = [d.message for d in hit if d.subject == "fixture.t"]
    assert len(t_msgs) == 2
    assert any("not int32" in m for m in t_msgs)
    assert any("no valid_range" in m for m in t_msgs)
    u_msgs = [d.message for d in hit if d.subject == "fixture.u"]
    assert len(u_msgs) == 1 and "outside" in u_msgs[0]
    assert rep.ok                      # warnings, not errors


def test_k006_output_revisited_across_outer_reduced_axis():
    s = KernelSpec("fixture", (4, 4),
                   [BlockOperand("o", "out", (8, 128), (32, 128),
                                 "float32", lambda i, j: (j, 0))])
    rep = check_kernels([s])
    hit = rep.filter(code="K006")
    assert len(hit) == 1
    assert hit.diagnostics[0].details == {"dependent_axes": [1],
                                          "reduced_axes": [0]}
    # the safe orientations: reduction innermost, or no reduction
    safe = KernelSpec("fixture", (4, 4),
                      [BlockOperand("o", "out", (8, 128), (32, 128),
                                    "float32", lambda i, j: (i, 0))])
    assert not check_kernels([safe]).filter(code="K006").diagnostics
    const = KernelSpec("fixture", (4, 4),
                       [BlockOperand("o", "out", (8, 128), (8, 128),
                                     "float32", lambda i, j: (0, 0))])
    assert not check_kernels([const]).filter(code="K006").diagnostics


def test_k006_size_one_axis_never_probed_or_warned():
    """A degenerate size-1 grid axis has no in-grid point to vary: the
    dependence probe must not evaluate a phantom out-of-grid index —
    a map reading that axis would look 'dependent' on it and draw a
    spurious revisit warning for a grid that writes each block once."""
    s = KernelSpec(
        "fixture", (4, 1),
        [BlockOperand("o", "out", (8, 128), (8, 128), "float32",
                      lambda i, j: (j, 0))])
    rep = check_kernels([s])
    assert not rep.filter(code="K006").diagnostics
    assert not rep.filter(code="K004").diagnostics


def test_k007_interpret_only_downgrade():
    """A CPU-test geometry (the engines' tiny shapes) declared
    interpret=True: the K001/K002 verdicts collapse into one K007 INFO
    — green CPU suites cannot claim TPU-readiness — and nothing errors."""
    s = pa.kernel_spec(B=2, KV=2, rep=2, W=1, D=16, block_size=4,
                       max_length=32, interpret=True)
    rep = check_kernels([s])
    assert rep.ok and not rep.warnings
    hit = rep.filter(code="K007")
    assert len(hit) == 1
    codes = {v["code"] for v in hit.diagnostics[0].details["violations"]}
    assert codes == {"K001", "K002"}    # D=16 lanes, bs=4 sublanes
    # the SAME spec not declared interpret errors on both rules
    hard = pa.kernel_spec(B=2, KV=2, rep=2, W=1, D=16, block_size=4,
                          max_length=32)
    rep = check_kernels([hard])
    assert not rep.ok
    assert {"K001", "K002"} <= set(_codes(rep))
    assert not rep.filter(code="K007").diagnostics


# ------------------------- 3. estimator parity + runtime guard


@pytest.mark.parametrize("cache_dtype", ["float32", "int8"])
def test_vmem_estimate_prices_the_real_call(monkeypatch, cache_dtype):
    """kernel_vmem_estimate's operand model == the pallas_call the
    kernel actually issues: capture the real grid_spec from an
    interpret-mode run and compare grid, per-operand block shapes,
    scratch shapes/dtypes, and scalar-prefetch count."""
    B, KV, rep_, W, D, bs, M, N = 3, 2, 2, 4, 16, 8, 4, 9
    quant = cache_dtype == "int8"
    captured = {}
    real = pa.pl.pallas_call

    def spy(kernel, **kw):
        captured.update(kw)
        return real(kernel, **kw)

    monkeypatch.setattr(pa.pl, "pallas_call", spy)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, KV * rep_, W, D).astype("float32"))
    tables = jnp.asarray(rng.randint(1, N, (B, M)).astype(np.int32))
    pos = jnp.asarray(rng.randint(0, M * bs - W, B).astype(np.int32))
    kw = {}
    if quant:
        pk = jnp.asarray(rng.randint(-127, 128,
                                     (N, KV, bs, D)).astype(np.int8))
        pv = jnp.asarray(rng.randint(-127, 128,
                                     (N, KV, bs, D)).astype(np.int8))
        kw = dict(k_scales=jnp.ones((N, KV, bs), jnp.float32),
                  v_scales=jnp.ones((N, KV, bs), jnp.float32))
    else:
        pk = jnp.asarray(rng.randn(N, KV, bs, D).astype("float32"))
        pv = jnp.asarray(rng.randn(N, KV, bs, D).astype("float32"))
    pa.paged_decode_attention(q, pk, pv, tables, pos, **kw)

    gs = captured["grid_spec"]
    spec = pa.kernel_spec(B=B, KV=KV, rep=rep_, W=W, D=D, block_size=bs,
                          max_length=M * bs, num_blocks=N,
                          q_dtype="float32", cache_dtype=cache_dtype,
                          tables=np.asarray(tables),
                          pos=np.asarray(pos), interpret=True)
    assert tuple(gs.grid) == spec.grid
    ins = [op for op in spec.operands if op.kind == "in"]
    outs = [op for op in spec.operands if op.kind == "out"]
    assert [tuple(s.block_shape) for s in gs.in_specs] == \
        [op.block_shape for op in ins]
    out_specs = gs.out_specs
    if not isinstance(out_specs, (list, tuple)):
        out_specs = [out_specs]
    assert [tuple(s.block_shape) for s in out_specs] == \
        [op.block_shape for op in outs]
    assert [(tuple(sc.shape), str(jnp.dtype(sc.dtype)))
            for sc in gs.scratch_shapes] == \
        [(sc.shape, str(jnp.dtype(sc.dtype))) for sc in spec.scratch]
    assert gs.num_scalar_prefetch == len(spec.prefetch)
    # byte totals agree when priced from the captured call's shapes
    rebuilt = KernelSpec(
        "captured", tuple(gs.grid),
        [BlockOperand(f"in{i}", "in", tuple(s.block_shape),
                      op.array_shape, op.dtype)
         for i, (s, op) in enumerate(zip(gs.in_specs, ins))]
        + [BlockOperand(f"out{i}", "out", tuple(s.block_shape),
                        op.array_shape, op.dtype)
           for i, (s, op) in enumerate(zip(out_specs, outs))],
        scratch=[ScratchOperand(f"s{i}", tuple(sc.shape), sc.dtype)
                 for i, sc in enumerate(gs.scratch_shapes)],
        prefetch=spec.prefetch)
    assert kernel_vmem_estimate(rebuilt)["total_bytes"] == \
        kernel_vmem_estimate(spec)["total_bytes"]


def test_m007_details_decompose_the_total():
    spec = pa.kernel_spec(B=4, KV=2, rep=4, W=8, D=128, block_size=32,
                          max_length=512, cache_dtype="int8")
    est = kernel_vmem_estimate(spec)
    assert est["total_bytes"] == \
        2 * (est["in_bytes"] + est["out_bytes"]) + est["scratch_bytes"]
    per_op = {n: b for n, _k, _s, _d, b in est["per_operand"]}
    # int8 page block (1, 1, 32, 128): one byte per element, no padding
    assert per_op["pool_k"] == 32 * 128
    # scale block (1, 1, 32) fp32: trailing (1, 32) pads to a whole
    # (8, 128) fp32 tile
    assert per_op["k_scales"] == 8 * 128 * 4
    # fp32 acc scratch (lanes=32, 128)
    assert per_op["acc"] == 32 * 128 * 4
    d = check_kernels([spec]).filter(code="M007").diagnostics[0]
    assert d.details["total_bytes"] == est["total_bytes"]


def test_runtime_guard_mirrors_static_rules(monkeypatch):
    """Satellite: on a non-interpret backend, TPU-illegal geometry
    raises a ValueError NAMING the violated K-rule before any lowering
    — not an opaque Mosaic error."""
    errs = pa.validate_call_geometry(64, 8, "int8")
    assert any(e.startswith("K001") for e in errs)
    assert any(e.startswith("K002") for e in errs)
    assert pa.validate_call_geometry(128, 32, "int8") == []
    assert pa.validate_call_geometry(128, 8, "float32") == []
    assert pa.validate_call_geometry(128, 8, "bfloat16") != []

    monkeypatch.setattr(pa.jax, "default_backend", lambda: "tpu")
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 4, 1, 16).astype("float32"))
    pk = jnp.asarray(rng.randn(5, 2, 4, 16).astype("float32"))
    tables = jnp.asarray(rng.randint(1, 5, (2, 3)).astype(np.int32))
    pos = jnp.asarray(np.array([3, 5], np.int32))
    with pytest.raises(ValueError) as ei:
        pa.paged_decode_attention(q, pk, pk, tables, pos)
    msg = str(ei.value)
    assert "K001" in msg and "K002" in msg
    assert "python -m mxtpu.analysis kernel" in msg


def test_runtime_guard_admits_legal_geometry_interpreted(monkeypatch):
    """The guard never fires in interpret mode (CPU tests run the
    engines' tiny geometries) and a TPU-legal geometry passes the guard
    itself — asserted via the validator the call path uses."""
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 4, 1, 16).astype("float32"))
    pk = jnp.asarray(rng.randn(5, 2, 4, 16).astype("float32"))
    tables = jnp.asarray(rng.randint(1, 5, (2, 3)).astype(np.int32))
    pos = jnp.asarray(np.array([3, 5], np.int32))
    out = pa.paged_decode_attention(q, pk, pk, tables, pos)
    assert out.shape == (2, 4, 1, 16)


# ---------------------------------------------- CLI + gate wiring

def test_cli_kernel_subcommand(capsys):
    from mxtpu.analysis.__main__ import main

    assert main(["kernel"]) == 0
    out = capsys.readouterr().out
    assert "M007" in out and "paged_attention" in out
    # a 1KiB ceiling fails every shipped kernel
    assert main(["kernel", "--vmem-budget", "1KiB"]) == 1
    assert "K003" in capsys.readouterr().out


def test_every_registered_pass_has_a_self_application():
    """The `all` gate cannot silently skip a pass: each registered name
    is wired to a probe, and an unwired name draws a P001 ERROR."""
    from mxtpu.analysis import __main__ as cli

    assert set(list_passes()) <= set(cli._SELF_APPLY)


def test_unwired_pass_fails_the_all_gate(monkeypatch):
    from mxtpu.analysis import __main__ as cli

    monkeypatch.setattr(cli, "list_passes", lambda: ["zz_new_pass"])
    rep = cli._self_apply_all()
    assert not rep.ok
    assert [d.code for d in rep.errors] == ["P001"]
    assert rep.errors[0].subject == "zz_new_pass"


# ------------------------------------- sharded (mesh-axis) geometry


def test_sharded_specs_verdict_per_shard_geometry():
    """A spec carrying ``mesh_axis=(axis, shards)`` prices the
    PER-DEVICE slice: the KV grid axis shrinks to KV/shards and the
    shard count is part of the spec name (so K diagnostics locate the
    sharded variant, not the global one)."""
    g = pa.kernel_spec(B=4, KV=8, rep=2, W=1, D=128, block_size=16,
                      max_length=256, cache_dtype="float32")
    s = pa.kernel_spec(B=4, KV=8, rep=2, W=1, D=128, block_size=16,
                      max_length=256, cache_dtype="float32",
                      mesh_axis=("tp", 4))
    assert s.grid[1] == g.grid[1] // 4
    assert "tp=4" in s.name
    assert check_kernels([s]).ok


def test_k003_per_shard_over_budget_fires_located_error():
    """Red team (ISSUE 16): the K003 budget applies to the PER-SHARD
    geometry — a sharded verify-window spec over a tightened budget
    fires a located ERROR whose subject names the tp-sharded spec."""
    spec = pa.kernel_spec(B=4, KV=8, rep=4, W=8, D=128, block_size=32,
                          max_length=512, cache_dtype="int8",
                          mesh_axis=("tp", 4))
    rep = check_kernels([spec], vmem_budget="64KiB")
    hit = rep.filter(code="K003")
    assert len(hit) == 1 and not rep.ok
    d = hit.diagnostics[0]
    assert d.severity is Severity.ERROR
    assert "tp=4" in d.subject
    assert d.details["budget_bytes"] == 64 * 1024
    # the same per-shard geometry is fine under the real 16MiB budget
    assert check_kernels([spec]).ok


def test_k009_mesh_axis_mismatch_fires_located_error():
    """Red team (ISSUE 16): a shard count that does not divide the
    global KV-head extent is a partitioning error — K009 ERROR locating
    the sharded spec, fired even for interpret-mode specs (it is a
    mesh/cache_spec mismatch, not a TPU tile rule)."""
    for interp in (False, True):
        spec = pa.kernel_spec(B=4, KV=6, rep=2, W=1, D=128,
                              block_size=32, max_length=256,
                              cache_dtype="float32",
                              mesh_axis=("tp", 4), interpret=interp)
        rep = check_kernels([spec])
        hit = rep.filter(code="K009")
        assert len(hit) == 1 and not rep.ok
        d = hit.diagnostics[0]
        assert d.severity is Severity.ERROR
        assert "tp" in d.message and "4" in d.message
        assert d.details["global_extent"] == 6
        assert d.details["shards"] == 4


def test_prefill_specs_in_the_merge_gate():
    """The chunked-prefill kernel lands behind the same gate: its
    specs (fp32 + int8 cache, incl. a tp-sharded variant) are part of
    default_kernel_specs() and verdict clean."""
    names = " ".join(s.name for s in default_kernel_specs())
    assert "paged_prefill[float32" in names
    assert "paged_prefill[int8" in names
    assert "paged_attention[float32,W=1,bs=16,D=128,tp=4" in names \
        or ("paged_attention" in names and "tp=4" in names)
    assert "paged_prefill" in names and "tp=4" in names
