"""Checkpoint format + FeedForward (parity: python/mxnet/model.py).

save_checkpoint writes ``prefix-symbol.json`` + ``prefix-%04d.params`` —
the format every reference-era tool reads (SURVEY §5 checkpoint/resume,
format (b)); params use the mx.nd.save container with arg:/aux: prefixes.
"""

from __future__ import annotations

import logging
import warnings
from collections import namedtuple

from . import ndarray as nd
from .base import MXTPUError

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "BatchEndParam", "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """(parity: model.save_checkpoint)"""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info("Saved checkpoint to \"%s\"", param_name)


def load_params(prefix, epoch):
    """(parity: model.load_params) → (arg_params, aux_params)"""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(parity: model.load_checkpoint) → (symbol, arg_params, aux_params)"""
    from . import symbol as sym
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Ancient pre-Module API (parity: model.FeedForward) — thin veneer
    over Module kept for checkpoint-era scripts."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        warnings.warn("FeedForward is deprecated; use mx.mod.Module or "
                      "Gluon instead (parity: the reference deprecated it "
                      "the same way)", DeprecationWarning)
        self._symbol = symbol
        self._ctx = ctx
        self._num_epoch = num_epoch
        self._optimizer = optimizer
        self._initializer = initializer
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._begin_epoch = begin_epoch
        self._kwargs = kwargs
        self._module = None

    @property
    def symbol(self):
        return self._symbol

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self._num_epoch or 0
        save_checkpoint(prefix, epoch, self._symbol,
                        self._arg_params or {}, self._aux_params or {})

    def _make_module(self, data_iter):
        from .module import Module
        label_names = [d[0] for d in (data_iter.provide_label or [])]
        data_names = [d[0] for d in data_iter.provide_data]
        mod = Module(self._symbol, data_names=data_names,
                     label_names=label_names, context=self._ctx)
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        data_iter = self._as_iter(X, y)
        self._module = self._make_module(data_iter)
        self._module.fit(data_iter, eval_data=eval_data,
                         eval_metric=eval_metric,
                         epoch_end_callback=epoch_end_callback,
                         batch_end_callback=batch_end_callback,
                         kvstore=kvstore, optimizer=self._optimizer,
                         optimizer_params=self._kwargs.get(
                             "optimizer_params",
                             (("learning_rate", 0.01),)),
                         initializer=self._initializer,
                         arg_params=self._arg_params,
                         aux_params=self._aux_params,
                         begin_epoch=self._begin_epoch,
                         num_epoch=self._num_epoch, monitor=monitor)
        self._arg_params, self._aux_params = self._module.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data_iter = self._as_iter(X, None)
        if self._module is None:
            self._module = self._make_module(data_iter)
            self._module.bind(data_shapes=data_iter.provide_data,
                              label_shapes=None, for_training=False)
            self._module.set_params(self._arg_params or {},
                                    self._aux_params or {})
        return self._module.predict(data_iter, num_batch=num_batch,
                                    reset=reset)

    @staticmethod
    def _as_iter(X, y):
        from .io import NDArrayIter, DataIter
        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size=128)
