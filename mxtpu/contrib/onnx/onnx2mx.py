"""ONNX → Symbol importer (parity: python/mxnet/contrib/onnx/onnx2mx/
import_model.py + import_onnx.py per-op translations).

Walks a ModelProto's graph.node list in order (ONNX graphs are already
topologically sorted by spec), mapping each node onto mxtpu Symbol ops;
initializers become arg/aux params as NDArrays.
"""

from __future__ import annotations

import numpy as np

from ...base import MXTPUError
from ... import ndarray as nd
from ... import symbol as sym_api
from . import onnx_pb as O

_IMPORTERS = {}


def register(*names):
    def deco(fn):
        for n in names:
            _IMPORTERS[n] = fn
        return fn
    return deco


def _attrs(node):
    out = {}
    for a in node.attribute:
        T = O.AttributeProto
        if a.type == T.INT:
            out[a.name] = int(a.i)
        elif a.type == T.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == T.STRING:
            out[a.name] = a.s.decode()
        elif a.type == T.INTS:
            out[a.name] = [int(x) for x in a.ints]
        elif a.type == T.FLOATS:
            out[a.name] = [float(x) for x in a.floats]
        elif a.type == T.TENSOR:
            out[a.name] = _tensor_to_np(a.t)
    return out


def _tensor_to_np(t):
    dtype = np.dtype(O.ONNX_TO_DTYPE[t.data_type])
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=dtype)
    elif t.float_data:
        arr = np.asarray(list(t.float_data), np.float32).astype(dtype)
    elif t.int64_data:
        arr = np.asarray(list(t.int64_data), np.int64).astype(dtype)
    elif t.int32_data:
        arr = np.asarray(list(t.int32_data), np.int32).astype(dtype)
    else:
        arr = np.zeros(0, dtype)
    return arr.reshape(tuple(t.dims))


class _Ctx:
    """Import state: name → Symbol plus constant lookups."""

    def __init__(self):
        self.syms = {}
        self.consts = {}   # name → np array (initializers / Constant nodes)
        self.params = {}   # initializer name → np array (for output params)
        self.param_used_as_input = set()

    def get(self, name):
        if name in self.syms:
            return self.syms[name]
        if name in self.consts:
            self.param_used_as_input.add(name)
            s = sym_api.Variable(name)
            self.syms[name] = s
            return s
        raise MXTPUError("ONNX import: undefined input %r" % name)

    def const_value(self, name):
        if name not in self.consts:
            raise MXTPUError(
                "ONNX import: %r must be a constant initializer" % name)
        return self.consts[name]


def _halve_pads(pads):
    if not pads:
        return None
    n = len(pads) // 2
    if list(pads[:n]) != list(pads[n:]):
        raise MXTPUError("ONNX import: asymmetric pads %r unsupported"
                         % (pads,))
    return tuple(pads[:n])


@register("Conv")
def _conv(node, ctx, at):
    w = ctx.const_value(node.input[1])
    kwargs = dict(kernel=tuple(at.get("kernel_shape", w.shape[2:])),
                  num_filter=int(w.shape[0]),
                  num_group=int(at.get("group", 1)),
                  no_bias=len(node.input) < 3)
    if at.get("strides"):
        kwargs["stride"] = tuple(at["strides"])
    if at.get("dilations"):
        kwargs["dilate"] = tuple(at["dilations"])
    p = _halve_pads(at.get("pads"))
    if p:
        kwargs["pad"] = p
    ins = [ctx.get(n) for n in node.input]
    return sym_api.Symbol._create("Convolution", None, ins, kwargs,
                                  name=node.name or None)


def _derived_const(ctx, base, arr):
    """Register a derived constant under a name that is guaranteed not to
    collide with a DIFFERENT tensor (a model may legitimately contain an
    initializer that happens to share our suffix convention); identical
    values are deduplicated."""
    name = base
    i = 0
    while name in ctx.consts:
        existing = ctx.consts[name]
        if (existing.shape == arr.shape and existing.dtype == arr.dtype
                and np.array_equal(existing, arr)):
            return name
        i += 1
        name = "%s_%d" % (base, i)
    ctx.consts[name] = arr
    return name


@register("Gemm")
def _gemm(node, ctx, at):
    if at.get("transA"):
        raise MXTPUError("ONNX import: Gemm transA unsupported")
    w_name = node.input[1]
    w = ctx.const_value(w_name)
    alpha = float(at.get("alpha", 1.0))
    beta = float(at.get("beta", 1.0))
    if alpha != 1.0:
        # fold alpha into the (constant) weight under a derived name
        w = w * np.asarray(alpha, w.dtype)
        w_name = _derived_const(ctx, w_name + "__mxtpu_a", w)
    rest = list(node.input[2:])
    if beta != 1.0 and rest:
        c_name = rest[0]
        if c_name not in ctx.consts:
            raise MXTPUError(
                "ONNX import: Gemm beta=%g with non-constant C input %r "
                "unsupported" % (beta, c_name))
        scaled = ctx.consts[c_name] * np.asarray(beta,
                                                 ctx.consts[c_name].dtype)
        rest[0] = _derived_const(ctx, c_name + "__mxtpu_b", scaled)
    if not at.get("transB", 0):
        # FullyConnected wants (num_hidden, in); register the transposed
        # weight under a fresh name instead of mutating the stored constant
        # — the same initializer may feed other consumers (shared weights),
        # which must keep seeing the original orientation.
        w = np.ascontiguousarray(w.T)
        w_name = _derived_const(ctx, w_name + "__mxtpu_T", w)
    kwargs = dict(num_hidden=int(w.shape[0]), flatten=False,
                  no_bias=not rest)
    ins = [ctx.get(n) for n in [node.input[0], w_name] + rest]
    return sym_api.Symbol._create("FullyConnected", None, ins, kwargs,
                                  name=node.name or None)


@register("BatchNormalization")
def _bn(node, ctx, at):
    ins = [ctx.get(n) for n in node.input]
    kwargs = dict(eps=float(at.get("epsilon", 1e-5)),
                  momentum=float(at.get("momentum", 0.9)),
                  fix_gamma=False, use_global_stats=False)
    for aux in node.input[3:5]:  # mean/var are aux states
        ctx.syms[aux]._node.attrs["__aux__"] = True
    return sym_api.Symbol._create("BatchNorm", None, ins, kwargs,
                                  name=node.name or None)


def _simple(mx_op, **fixed):
    def imp(node, ctx, at):
        ins = [ctx.get(n) for n in node.input]
        return sym_api.Symbol._create(mx_op, None, ins, dict(fixed),
                                      name=node.name or None)
    return imp


for _ox, _mx in [("Relu", "relu"), ("Sigmoid", "sigmoid"), ("Tanh", "tanh"),
                 ("Exp", "exp"), ("Log", "log"), ("Sqrt", "sqrt"),
                 ("Abs", "abs"), ("Neg", "negative"), ("Erf", "erf"),
                 ("Floor", "floor"), ("Ceil", "ceil"),
                 ("Identity", "identity"),
                 ("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
                 ("Mul", "broadcast_mul"), ("Div", "broadcast_div"),
                 # MatMul → batch_dot (= jnp.matmul): ONNX MatMul batches
                 # over leading dims for rank>2, which MXNet dot does NOT
                 # (dot contracts last axis x first axis); batch_dot matches
                 # MatMul for every rank.
                 ("Pow", "broadcast_power"), ("MatMul", "batch_dot"),
                 ("Max", "broadcast_maximum"), ("Min", "broadcast_minimum"),
                 ("Sum", "add_n")]:
    register(_ox)(_simple(_mx))

register("Softplus")(_simple("Activation", act_type="softrelu"))
register("Softsign")(_simple("Activation", act_type="softsign"))
register("GlobalAveragePool")(_simple("Pooling", pool_type="avg",
                                      global_pool=True))
register("GlobalMaxPool")(_simple("Pooling", pool_type="max",
                                  global_pool=True))
register("PRelu")(_simple("LeakyReLU", act_type="prelu"))


@register("LeakyRelu")
def _leaky(node, ctx, at):
    return sym_api.Symbol._create(
        "LeakyReLU", None, [ctx.get(node.input[0])],
        dict(act_type="leaky", slope=float(at.get("alpha", 0.01))),
        name=node.name or None)


@register("Elu")
def _elu(node, ctx, at):
    return sym_api.Symbol._create(
        "LeakyReLU", None, [ctx.get(node.input[0])],
        dict(act_type="elu", slope=float(at.get("alpha", 1.0))),
        name=node.name or None)


@register("MaxPool", "AveragePool")
def _pool(node, ctx, at):
    kwargs = dict(kernel=tuple(at["kernel_shape"]),
                  pool_type="max" if node.op_type == "MaxPool" else "avg",
                  pooling_convention="full" if at.get("ceil_mode") else
                  "valid")
    if at.get("strides"):
        kwargs["stride"] = tuple(at["strides"])
    p = _halve_pads(at.get("pads"))
    if p:
        kwargs["pad"] = p
    if node.op_type == "AveragePool":
        kwargs["count_include_pad"] = bool(at.get("count_include_pad", 0))
    return sym_api.Symbol._create("Pooling", None,
                                  [ctx.get(node.input[0])], kwargs,
                                  name=node.name or None)


@register("Flatten")
def _flatten(node, ctx, at):
    if at.get("axis", 1) != 1:
        raise MXTPUError("ONNX import: Flatten axis != 1 unsupported")
    return sym_api.Symbol._create("Flatten", None,
                                  [ctx.get(node.input[0])], {},
                                  name=node.name or None)


@register("Reshape")
def _reshape(node, ctx, at):
    shape = tuple(int(x) for x in ctx.const_value(node.input[1]))
    return sym_api.Symbol._create("reshape", None,
                                  [ctx.get(node.input[0])],
                                  dict(shape=shape),
                                  name=node.name or None)


@register("Transpose")
def _transpose(node, ctx, at):
    kwargs = {}
    if at.get("perm") is not None:
        kwargs["axes"] = tuple(at["perm"])
    return sym_api.Symbol._create("transpose", None,
                                  [ctx.get(node.input[0])], kwargs,
                                  name=node.name or None)


@register("Concat")
def _concat(node, ctx, at):
    ins = [ctx.get(n) for n in node.input]
    return sym_api.Symbol._create("concat", None, ins,
                                  dict(dim=int(at.get("axis", 1))),
                                  name=node.name or None)


@register("Softmax")
def _softmax(node, ctx, at):
    return sym_api.Symbol._create("softmax", None,
                                  [ctx.get(node.input[0])],
                                  dict(axis=int(at.get("axis", -1))),
                                  name=node.name or None)


@register("Dropout")
def _dropout(node, ctx, at):
    p = at.get("ratio", 0.5)
    if len(node.input) > 1 and node.input[1]:
        p = float(ctx.const_value(node.input[1]))
    return sym_api.Symbol._create("Dropout", None,
                                  [ctx.get(node.input[0])], dict(p=p),
                                  name=node.name or None)


@register("Cast")
def _cast(node, ctx, at):
    dtype = O.ONNX_TO_DTYPE[at["to"]]
    return sym_api.Symbol._create("cast", None, [ctx.get(node.input[0])],
                                  dict(dtype=dtype),
                                  name=node.name or None)


@register("Gather")
def _gather(node, ctx, at):
    ins = [ctx.get(node.input[0]), ctx.get(node.input[1])]
    return sym_api.Symbol._create("take", None, ins,
                                  dict(axis=int(at.get("axis", 0))),
                                  name=node.name or None)


@register("Clip")
def _clip(node, ctx, at):
    # opset-6 style puts min/max in attributes; opset-11+ passes them as
    # optional inputs whose name is "" when omitted.  Branch explicitly —
    # never evaluate const_value("") (dict.get defaults are eager).
    def bound(attr, idx, default):
        if attr in at:
            return float(at[attr])
        if len(node.input) > idx and node.input[idx]:
            return float(ctx.const_value(node.input[idx]))
        return default

    a_min = bound("min", 1, -np.inf)
    a_max = bound("max", 2, np.inf)
    return sym_api.Symbol._create("clip", None, [ctx.get(node.input[0])],
                                  dict(a_min=float(a_min),
                                       a_max=float(a_max)),
                                  name=node.name or None)


@register("ReduceMean", "ReduceMax", "ReduceMin", "ReduceProd")
def _reduce(node, ctx, at):
    mx_op = {"ReduceMean": "mean", "ReduceMax": "max", "ReduceMin": "min",
             "ReduceProd": "prod"}[node.op_type]
    axes = at.get("axes")
    kwargs = dict(keepdims=bool(at.get("keepdims", 1)))
    if axes is not None:
        kwargs["axis"] = tuple(axes)
    return sym_api.Symbol._create(mx_op, None, [ctx.get(node.input[0])],
                                  kwargs, name=node.name or None)


@register("ReduceSum")
def _reduce_sum(node, ctx, at):
    kwargs = dict(keepdims=bool(at.get("keepdims", 1)))
    if len(node.input) > 1 and node.input[1]:
        kwargs["axis"] = tuple(int(x)
                               for x in ctx.const_value(node.input[1]))
    elif at.get("axes") is not None:
        kwargs["axis"] = tuple(at["axes"])
    return sym_api.Symbol._create("sum", None, [ctx.get(node.input[0])],
                                  kwargs, name=node.name or None)


@register("Unsqueeze")
def _unsqueeze(node, ctx, at):
    if len(node.input) > 1:
        axes = [int(x) for x in ctx.const_value(node.input[1])]
    else:
        axes = at["axes"]
    s = ctx.get(node.input[0])
    for ax in axes:
        s = sym_api.Symbol._create("expand_dims", None, [s],
                                   dict(axis=int(ax)))
    return s


@register("Slice")
def _slice(node, ctx, at):
    starts = [int(x) for x in ctx.const_value(node.input[1])]
    ends = [int(x) for x in ctx.const_value(node.input[2])]
    axes = ([int(x) for x in ctx.const_value(node.input[3])]
            if len(node.input) > 3 else list(range(len(starts))))
    s = ctx.get(node.input[0])
    big = np.iinfo(np.int64).max
    for st, en, ax in zip(starts, ends, axes):
        s = sym_api.Symbol._create(
            "slice_axis", None, [s],
            dict(axis=ax, begin=st, end=None if en >= big else en))
    return s


@register("Constant")
def _constant(node, ctx, at):
    ctx.consts[node.output[0]] = at["value"]
    return None


def import_model(model_file):
    """Import an ONNX file → (sym, arg_params, aux_params) (parity:
    mx.contrib.onnx.import_model)."""
    model = O.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    return _import_graph(model.graph)


def get_model_metadata(model_file):
    """Input/output names and shapes (parity: get_model_metadata)."""
    model = O.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph
    inits = {t.name for t in g.initializer}

    def vi_shape(vi):
        return (vi.name, tuple(d.dim_value
                               for d in vi.type.tensor_type.shape.dim))
    return {"input_tensor_data": [vi_shape(v) for v in g.input
                                  if v.name not in inits],
            "output_tensor_data": [vi_shape(v) for v in g.output]}


def _import_graph(g):
    ctx = _Ctx()
    for t in g.initializer:
        arr = _tensor_to_np(t)
        ctx.consts[t.name] = arr
        ctx.params[t.name] = arr
    inits = set(ctx.consts)
    for vi in g.input:
        if vi.name not in inits:
            ctx.syms[vi.name] = sym_api.Variable(vi.name)

    for node in g.node:
        imp = _IMPORTERS.get(node.op_type)
        if imp is None:
            raise MXTPUError("ONNX import: unsupported op %r (node %r)" %
                             (node.op_type, node.name))
        out = imp(node, ctx, _attrs(node))
        if out is None:
            continue
        if len(node.output) == 1:
            ctx.syms[node.output[0]] = out
        else:
            for i, oname in enumerate(node.output):
                if oname:
                    ctx.syms[oname] = out[i]

    outs = [ctx.syms[v.name] for v in g.output]
    sym = outs[0] if len(outs) == 1 else sym_api.Group(outs)

    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    for name in ctx.param_used_as_input:
        # Gemm import may have registered derived constants (transposed
        # weights under fresh names) — read the constant table, not the
        # original proto.
        arr = nd.array(ctx.consts[name])
        if name in aux_names:
            aux_params[name] = arr
        elif name in arg_names:
            arg_params[name] = arr
    return sym, arg_params, aux_params
