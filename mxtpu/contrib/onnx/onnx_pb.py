"""Protobuf bindings for the vendored ONNX schema subset (onnx.proto).

The checked-in ``onnx_pb2.py`` is regenerated with the in-image ``protoc``
if it is missing or was built by an incompatible protobuf generation.
"""

import os
import subprocess

_HERE = os.path.dirname(os.path.abspath(__file__))


def _regen():
    subprocess.run(["protoc", "--python_out=.", "onnx.proto"],
                   cwd=_HERE, check=True)


try:
    from . import onnx_pb2
except Exception:  # stale generated code vs protobuf runtime
    _regen()
    from . import onnx_pb2

AttributeProto = onnx_pb2.AttributeProto
GraphProto = onnx_pb2.GraphProto
ModelProto = onnx_pb2.ModelProto
NodeProto = onnx_pb2.NodeProto
OperatorSetIdProto = onnx_pb2.OperatorSetIdProto
TensorProto = onnx_pb2.TensorProto
TensorShapeProto = onnx_pb2.TensorShapeProto
TypeProto = onnx_pb2.TypeProto
ValueInfoProto = onnx_pb2.ValueInfoProto

# numpy dtype name <-> TensorProto.DataType
DTYPE_TO_ONNX = {
    "float32": TensorProto.FLOAT, "float64": TensorProto.DOUBLE,
    "float16": TensorProto.FLOAT16, "bfloat16": TensorProto.BFLOAT16,
    "int8": TensorProto.INT8, "uint8": TensorProto.UINT8,
    "int16": TensorProto.INT16, "uint16": TensorProto.UINT16,
    "int32": TensorProto.INT32, "int64": TensorProto.INT64,
    "bool": TensorProto.BOOL,
}
ONNX_TO_DTYPE = {v: k for k, v in DTYPE_TO_ONNX.items()}
