"""Symbol → ONNX exporter (parity: python/mxnet/contrib/onnx/mx2onnx/
export_model.py + _op_translations.py).

The reference walks the symbol json node list and applies per-op converter
functions registered by name; this does the same over the mxtpu Symbol DAG
(a topo walk of `_Node`s), emitting a standard `ModelProto` through the
vendored wire-compatible schema (onnx.proto) so the output loads in stock
ONNX runtimes.
"""

from __future__ import annotations

import numpy as np

from ...base import MXTPUError
from ...ndarray import NDArray
from . import onnx_pb as O

OPSET = 13
_CONVERTERS = {}


def register(*op_names):
    def deco(fn):
        from ...base import get_op
        for n in op_names:
            try:
                n = get_op(n).name  # canonicalize: node.op stores this
            except Exception:
                pass
            _CONVERTERS[n] = fn
        return fn
    return deco


class _Builder:
    def __init__(self):
        self.nodes = []
        self.initializers = {}
        self._uid = 0
        self.shapes = {}  # (id(node), out_idx) -> shape, from infer_shape

    def shape_of(self, sym):
        """Inferred shape of an input Symbol, or None if unknown."""
        return self.shapes.get((id(sym._node), sym._index))

    def uniq(self, base):
        self._uid += 1
        return "%s__%d" % (base, self._uid)

    def node(self, op_type, inputs, outputs, name=None, **attrs):
        n = O.NodeProto()
        n.op_type = op_type
        n.input.extend(inputs)
        n.output.extend(outputs)
        n.name = name or self.uniq(op_type)
        for k, v in attrs.items():
            if v is None:
                continue
            n.attribute.append(_attr(k, v))
        self.nodes.append(n)
        return outputs[0]

    def tensor(self, name, arr):
        arr = np.ascontiguousarray(arr)
        t = O.TensorProto()
        t.name = name
        t.dims.extend(arr.shape)
        t.data_type = O.DTYPE_TO_ONNX[str(arr.dtype)]
        t.raw_data = arr.tobytes()
        self.initializers[name] = t
        return name

    def const(self, base, arr):
        return self.tensor(self.uniq(base), np.asarray(arr))


def _attr(name, v):
    a = O.AttributeProto()
    a.name = name
    if isinstance(v, bool):
        a.type, a.i = O.AttributeProto.INT, int(v)
    elif isinstance(v, int):
        a.type, a.i = O.AttributeProto.INT, v
    elif isinstance(v, float):
        a.type, a.f = O.AttributeProto.FLOAT, v
    elif isinstance(v, str):
        a.type, a.s = O.AttributeProto.STRING, v.encode()
    elif isinstance(v, (list, tuple)):
        if all(isinstance(x, (int, np.integer)) for x in v):
            a.type = O.AttributeProto.INTS
            a.ints.extend(int(x) for x in v)
        else:
            a.type = O.AttributeProto.FLOATS
            a.floats.extend(float(x) for x in v)
    else:
        raise MXTPUError("unsupported ONNX attribute %r=%r" % (name, v))
    return a


def _in(node, i):
    return node.inputs[i].name if i < len(node.inputs) else ""


def _pads(pad, ndim):
    pad = tuple(pad) if pad else (0,) * ndim
    return list(pad) + list(pad)  # symmetric begin+end


# ---------------------------------------------------------------- nn ops

@register("FullyConnected")
def _fc(node, b, out):
    kw = node.kwargs
    data = _in(node, 0)
    if kw.get("flatten", True):
        data = b.node("Flatten", [data], [b.uniq(node.name + "_flat")],
                      axis=1)
    ins = [data, _in(node, 1)]
    if not kw.get("no_bias", False) and len(node.inputs) > 2:
        ins.append(_in(node, 2))
    b.node("Gemm", ins, [out], name=node.name, alpha=1.0, beta=1.0,
           transA=0, transB=1)


@register("Convolution")
def _conv(node, b, out):
    kw = node.kwargs
    kernel = tuple(kw.get("kernel", ()))
    ndim = len(kernel)
    ins = [_in(node, 0), _in(node, 1)]
    if not kw.get("no_bias", False) and len(node.inputs) > 2:
        ins.append(_in(node, 2))
    b.node("Conv", ins, [out], name=node.name,
           kernel_shape=list(kernel),
           strides=list(kw.get("stride") or (1,) * ndim),
           dilations=list(kw.get("dilate") or (1,) * ndim),
           pads=_pads(kw.get("pad"), ndim),
           group=int(kw.get("num_group", 1)))


@register("Pooling")
def _pool(node, b, out):
    kw = node.kwargs
    ptype = kw.get("pool_type", "max")
    if kw.get("global_pool", False):
        b.node("GlobalMaxPool" if ptype == "max" else "GlobalAveragePool",
               [_in(node, 0)], [out], name=node.name)
        return
    kernel = tuple(kw.get("kernel", ()))
    ndim = len(kernel)
    attrs = dict(kernel_shape=list(kernel),
                 strides=list(kw.get("stride") or (1,) * ndim),
                 pads=_pads(kw.get("pad"), ndim),
                 ceil_mode=int(kw.get("pooling_convention", "valid")
                               == "full"))
    if ptype == "max":
        b.node("MaxPool", [_in(node, 0)], [out], name=node.name, **attrs)
    else:
        attrs["count_include_pad"] = int(kw.get("count_include_pad", True))
        b.node("AveragePool", [_in(node, 0)], [out], name=node.name,
               **attrs)


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@register("Activation")
def _act(node, b, out):
    act = node.kwargs.get("act_type", "relu")
    if act not in _ACT:
        raise MXTPUError("ONNX export: unsupported act_type %r" % act)
    b.node(_ACT[act], [_in(node, 0)], [out], name=node.name)


@register("LeakyReLU")
def _leaky(node, b, out):
    act = node.kwargs.get("act_type", "leaky")
    slope = float(node.kwargs.get("slope", 0.25))
    if act == "leaky":
        b.node("LeakyRelu", [_in(node, 0)], [out], name=node.name,
               alpha=slope)
    elif act == "elu":
        b.node("Elu", [_in(node, 0)], [out], name=node.name, alpha=slope)
    elif act == "prelu":
        b.node("PRelu", [_in(node, 0), _in(node, 1)], [out],
               name=node.name)
    else:
        raise MXTPUError("ONNX export: unsupported LeakyReLU %r" % act)


@register("BatchNorm")
def _bn(node, b, out):
    kw = node.kwargs
    ins = [_in(node, i) for i in range(5)]
    if kw.get("fix_gamma", True):
        # reference semantics: gamma is ignored (treated as ones) when
        # fix_gamma — ONNX BatchNormalization always applies scale, so
        # emit an explicit ones initializer
        gamma_name = node.inputs[1].name
        n_ch = b.initializers.get(gamma_name)
        dim = int(n_ch.dims[0]) if n_ch is not None else None
        if dim is None:
            raise MXTPUError(
                "ONNX export: BatchNorm %r with fix_gamma needs gamma "
                "param to infer channels" % node.name)
        ins[1] = b.const(node.name + "_fixed_gamma",
                         np.ones(dim, np.float32))
    b.node("BatchNormalization", ins, [out], name=node.name,
           epsilon=float(kw.get("eps", 1e-5)),
           momentum=float(kw.get("momentum", 0.9)))


@register("Dropout")
def _dropout(node, b, out):
    ratio = b.const(node.name + "_ratio",
                    np.float32(node.kwargs.get("p", 0.5)))
    b.node("Dropout", [_in(node, 0), ratio], [out], name=node.name)


@register("softmax", "SoftmaxActivation")
def _softmax(node, b, out):
    b.node("Softmax", [_in(node, 0)], [out], name=node.name,
           axis=int(node.kwargs.get("axis", -1)))


@register("SoftmaxOutput")
def _softmax_out(node, b, out):
    # inference export: the label input and loss are dropped (reference
    # mx2onnx does the same), leaving plain softmax over the last axis
    b.node("Softmax", [_in(node, 0)], [out], name=node.name, axis=-1)


@register("Embedding")
def _embedding(node, b, out):
    idx = b.node("Cast", [_in(node, 0)], [b.uniq(node.name + "_idx")],
                 to=O.TensorProto.INT64)
    b.node("Gather", [_in(node, 1), idx], [out], name=node.name, axis=0)


# ------------------------------------------------------------ tensor ops

@register("Flatten")
def _flatten(node, b, out):
    b.node("Flatten", [_in(node, 0)], [out], name=node.name, axis=1)


@register("reshape", "Reshape")
def _reshape(node, b, out):
    shape = node.kwargs.get("shape")
    sh = b.const(node.name + "_shape", np.asarray(shape, np.int64))
    b.node("Reshape", [_in(node, 0), sh], [out], name=node.name)


@register("transpose")
def _transpose(node, b, out):
    axes = node.kwargs.get("axes")
    b.node("Transpose", [_in(node, 0)], [out], name=node.name,
           perm=list(axes) if axes else None)


@register("concat", "Concat")
def _concat(node, b, out):
    b.node("Concat", [_in(node, i) for i in range(len(node.inputs))],
           [out], name=node.name, axis=int(node.kwargs.get("dim", 1)))


@register("expand_dims")
def _expand_dims(node, b, out):
    ax = b.const(node.name + "_axes",
                 np.asarray([node.kwargs.get("axis", 0)], np.int64))
    b.node("Unsqueeze", [_in(node, 0), ax], [out], name=node.name)


@register("slice_axis")
def _slice_axis(node, b, out):
    kw = node.kwargs
    end = kw.get("end")
    end = np.iinfo(np.int64).max if end is None else end
    b.node("Slice",
           [_in(node, 0),
            b.const(node.name + "_st", np.asarray([kw["begin"]], np.int64)),
            b.const(node.name + "_en", np.asarray([end], np.int64)),
            b.const(node.name + "_ax", np.asarray([kw["axis"]], np.int64))],
           [out], name=node.name)


def _binary(onnx_op):
    def conv(node, b, out):
        b.node(onnx_op, [_in(node, 0), _in(node, 1)], [out],
               name=node.name)
    return conv


for _mx, _ox in [("elemwise_add", "Add"), ("broadcast_add", "Add"),
                 ("elemwise_sub", "Sub"), ("broadcast_sub", "Sub"),
                 ("elemwise_mul", "Mul"), ("broadcast_mul", "Mul"),
                 ("elemwise_div", "Div"), ("broadcast_div", "Div"),
                 ("broadcast_maximum", "Max"),
                 ("broadcast_minimum", "Min"), ("broadcast_power", "Pow")]:
    register(_mx)(_binary(_ox))


@register("dot")
def _dot(node, b, out):
    # MXNet dot contracts the LAST axis of lhs with the FIRST axis of rhs;
    # ONNX MatMul matches that only for <=2-D operands (for higher ranks
    # MatMul batches over the leading dims instead).  Refuse rather than
    # export a silently wrong graph.
    for i in (0, 1):
        shp = b.shape_of(node.inputs[i])
        if shp is None:
            raise MXTPUError(
                "ONNX export: cannot verify operand rank of dot node %r "
                "(shape inference did not reach it); dot is only "
                "exportable for 2-D operands" % node.name)
        if len(shp) > 2:
            raise MXTPUError(
                "ONNX export: dot with %d-D input %r has last-axis x "
                "first-axis contraction semantics that MatMul does not "
                "match; reshape to 2-D before dot" %
                (len(shp), node.inputs[i].name))
    a_name, b_name = _in(node, 0), _in(node, 1)
    kw = node.kwargs
    # transpose on a 1-D operand is a no-op in MXNet dot (ops/tensor.py);
    # emitting perm=[1,0] on a rank-1 tensor would be an invalid graph
    if kw.get("transpose_a") and len(b.shape_of(node.inputs[0])) >= 2:
        a_name = b.node("Transpose", [a_name], [b.uniq(node.name + "_tA")],
                        perm=[1, 0])
    if kw.get("transpose_b") and len(b.shape_of(node.inputs[1])) >= 2:
        b_name = b.node("Transpose", [b_name], [b.uniq(node.name + "_tB")],
                        perm=[1, 0])
    b.node("MatMul", [a_name, b_name], [out], name=node.name)


@register("batch_dot")
def _batch_dot(node, b, out):
    """batch_dot == jnp.matmul == ONNX MatMul for every rank; transposes
    swap the last two axes, which needs the operand rank for the perm."""
    a_name, b_name = _in(node, 0), _in(node, 1)
    kw = node.kwargs

    def swap_last2(name, i, tag):
        shp = b.shape_of(node.inputs[i])
        if shp is None or len(shp) < 2:
            raise MXTPUError(
                "ONNX export: batch_dot transpose needs a known >=2-D "
                "operand rank for node %r" % node.name)
        perm = list(range(len(shp)))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        return b.node("Transpose", [name], [b.uniq(node.name + tag)],
                      perm=perm)

    if kw.get("transpose_a"):
        a_name = swap_last2(a_name, 0, "_tA")
    if kw.get("transpose_b"):
        b_name = swap_last2(b_name, 1, "_tB")
    b.node("MatMul", [a_name, b_name], [out], name=node.name)


def _scalar(onnx_op, rev=False):
    def conv(node, b, out):
        c = b.const(node.name + "_s",
                    np.float32(node.kwargs.get("scalar", 0.0)))
        ins = [c, _in(node, 0)] if rev else [_in(node, 0), c]
        b.node(onnx_op, ins, [out], name=node.name)
    return conv


for _mx, _ox, _rev in [("_plus_scalar", "Add", False),
                       ("_minus_scalar", "Sub", False),
                       ("_rminus_scalar", "Sub", True),
                       ("_mul_scalar", "Mul", False),
                       ("_div_scalar", "Div", False),
                       ("_rdiv_scalar", "Div", True),
                       ("_power_scalar", "Pow", False)]:
    register(_mx)(_scalar(_ox, _rev))


def _unary(onnx_op):
    def conv(node, b, out):
        b.node(onnx_op, [_in(node, 0)], [out], name=node.name)
    return conv


for _mx, _ox in [("relu", "Relu"), ("sigmoid", "Sigmoid"), ("tanh", "Tanh"),
                 ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"),
                 ("abs", "Abs"), ("negative", "Neg"), ("floor", "Floor"),
                 ("ceil", "Ceil"), ("erf", "Erf"), ("identity", "Identity"),
                 ("BlockGrad", "Identity"), ("softsign", "Softsign")]:
    register(_mx)(_unary(_ox))


@register("add_n", "ElementWiseSum")
def _add_n(node, b, out):
    b.node("Sum", [_in(node, i) for i in range(len(node.inputs))], [out],
           name=node.name)


@register("clip")
def _clip(node, b, out):
    b.node("Clip",
           [_in(node, 0),
            b.const(node.name + "_min",
                    np.float32(node.kwargs.get("a_min", 0.0))),
            b.const(node.name + "_max",
                    np.float32(node.kwargs.get("a_max", 0.0)))],
           [out], name=node.name)


def _reduce(onnx_op):
    def conv(node, b, out):
        kw = node.kwargs
        axis = kw.get("axis")
        if axis is None:
            axes = None
        else:
            axes = [axis] if isinstance(axis, int) else list(axis)
        b.node(onnx_op, [_in(node, 0)], [out], name=node.name, axes=axes,
               keepdims=int(kw.get("keepdims", False)))
    return conv


for _mx, _ox in [("mean", "ReduceMean"), ("max", "ReduceMax"),
                 ("min", "ReduceMin"), ("prod", "ReduceProd")]:
    register(_mx)(_reduce(_ox))


@register("sum", "sum_axis")
def _sum(node, b, out):
    kw = node.kwargs
    axis = kw.get("axis")
    ins = [_in(node, 0)]
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        ins.append(b.const(node.name + "_axes",
                           np.asarray(axes, np.int64)))
    b.node("ReduceSum", ins, [out], name=node.name,
           keepdims=int(kw.get("keepdims", False)))


# ------------------------------------------------------------- exporter

def export_model(sym, params, input_shape, input_type=np.float32,
                 onnx_file_path="model.onnx", verbose=False):
    """Export a Symbol + params to an ONNX file (parity:
    mx.contrib.onnx.export_model).

    input_shape: list of shapes, one per data input (in list_arguments
    order of the non-param inputs).  Returns the output path.
    """
    from ...symbol import Symbol, load as sym_load

    if isinstance(sym, str):
        sym = sym_load(sym)
    if isinstance(params, str):
        from ... import ndarray as nd
        loaded = nd.load(params)
        params = {k.split(":", 1)[-1]: v for k, v in loaded.items()}
    params = {k.split(":", 1)[-1]: (v.asnumpy() if isinstance(v, NDArray)
                                    else np.asarray(v))
              for k, v in params.items()}
    if not isinstance(input_shape, list):
        input_shape = [input_shape]

    b = _Builder()
    graph = O.GraphProto()
    graph.name = sym.name

    data_names = [n for n in sym.list_arguments() if n not in params] + \
        [n for n in sym.list_auxiliary_states() if n not in params]
    if len(data_names) != len(input_shape):
        raise MXTPUError(
            "export_model: %d data inputs %s but %d input shapes" %
            (len(data_names), data_names, len(input_shape)))
    dtype_name = np.dtype(input_type).name

    for name, shape in zip(data_names, input_shape):
        vi = graph.input.add()
        vi.name = name
        vi.type.tensor_type.elem_type = O.DTYPE_TO_ONNX[dtype_name]
        for d in shape:
            vi.type.tensor_type.shape.dim.add().dim_value = int(d)

    for name, arr in params.items():
        b.tensor(name, arr)

    # Per-node output shapes for converters that need rank information
    # (e.g. dot).  Partial inference: nodes whose shapes cannot be derived
    # simply stay absent from the map — converters that REQUIRE rank info
    # (dot) raise loudly on absence rather than exporting a wrong graph.
    # Skipped entirely when no rank-dependent op is in the graph: the
    # common CNN export shouldn't pay a second abstract-eval graph walk.
    _RANK_DEPENDENT = {"dot", "batch_dot"}
    if any(n.op in _RANK_DEPENDENT for n in sym._topo()):
        try:
            internals = sym.get_internals()
            known = dict(zip(data_names, (tuple(s) for s in input_shape)))
            known.update({k: tuple(v.shape) for k, v in params.items()})
            _, int_shapes, _ = internals._infer_shape_impl(
                partial=True, known_shapes=known)
            if int_shapes:
                for (n, idx), shp in zip(internals._output_entries(),
                                         int_shapes):
                    if shp is not None:
                        b.shapes[(id(n), idx)] = tuple(shp)
        except Exception as e:  # rank-needing converters fail closed
            import warnings
            warnings.warn("ONNX export: shape inference failed (%s); "
                          "rank-dependent converters will reject" % (e,))

    converted_params = set(params)
    for node in sym._topo():
        if node.op is None:  # variable: already an input or initializer
            if node.name not in converted_params and \
                    node.name not in data_names:
                raise MXTPUError("export_model: no value for variable %r"
                                 % node.name)
            continue
        conv = _CONVERTERS.get(node.op)
        if conv is None:
            raise MXTPUError(
                "ONNX export: no converter for op %r (node %r)" %
                (node.op, node.name))
        out_name = node.name if node.num_outputs == 1 else \
            "%s_output0" % node.name
        conv(node, b, out_name)
        if verbose:
            print("converted %s -> %s" % (node.op, out_name))

    graph.node.extend(b.nodes)
    graph.initializer.extend(b.initializers.values())

    # output value info with inferred shapes; reuse the internals pass
    # when it already ran rather than paying a second abstract-eval walk
    out_names = [n.name for n in sym._roots()]
    if b.shapes:
        out_shapes = [b.shapes.get((id(n), i))
                      for n, i in sym._output_entries()]
    else:
        shape_kwargs = dict(zip(data_names, input_shape))
        try:
            _, out_shapes, _ = sym.infer_shape(**shape_kwargs)
        except Exception:
            out_shapes = None
    if out_shapes is None:  # infer_shape may also RETURN (None,)*3
        out_shapes = [None] * len(out_names)
    for name, shape in zip(out_names, out_shapes):
        vi = graph.output.add()
        vi.name = name
        vi.type.tensor_type.elem_type = O.DTYPE_TO_ONNX[dtype_name]
        if shape:
            for d in shape:
                vi.type.tensor_type.shape.dim.add().dim_value = int(d)

    model = O.ModelProto()
    model.ir_version = 8
    model.producer_name = "mxtpu"
    model.producer_version = "3.0"
    opset = model.opset_import.add()
    opset.domain = ""
    opset.version = OPSET
    model.graph.CopyFrom(graph)

    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path
