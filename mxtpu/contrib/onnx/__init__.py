"""ONNX interop (parity: python/mxnet/contrib/onnx/).

`export_model` serializes a Symbol + params to a standard ONNX ModelProto
(wire-compatible vendored schema — the `onnx` pip package is not required);
`import_model` builds a Symbol + params back from one.
"""

from .mx2onnx import export_model
from .onnx2mx import import_model, get_model_metadata

__all__ = ["export_model", "import_model", "get_model_metadata"]
