"""mx.contrib namespace (parity: python/mxnet/contrib/).

Members: onnx (mx2onnx exporter + onnx2mx importer), amp (re-exported —
the implementation lives in mxtpu.amp), quantization (INT8 PTQ), text
(vocab/embeddings — see gluon.contrib as well).
"""

from .. import amp  # noqa: F401  (mx.contrib.amp alias)
from . import onnx  # noqa: F401
from . import quantization  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import text  # noqa: F401
from . import orbax_ckpt  # noqa: F401 — sharded checkpointing adapter
