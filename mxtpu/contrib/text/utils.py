"""Text helpers (parity: python/mxnet/contrib/text/utils.py)."""

import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Tokenize a string and count tokens (parity:
    count_tokens_from_str)."""
    source_str = re.sub("[%s%s]" % (token_delim, seq_delim), " ",
                        source_str)
    tokens = [t for t in source_str.split(" ") if t]
    if to_lower:
        tokens = [t.lower() for t in tokens]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter
