"""Token embeddings (parity: python/mxnet/contrib/text/embedding.py —
`TokenEmbedding` registry + from-file loaders + CompositeEmbedding).

The reference downloads pretrained GloVe/FastText tables; with zero
network here the same classes load from local files in the identical
text format (`token v1 v2 ... vN` per line, optional fastText header
line), so user-supplied pretrained files work unchanged.
"""

from __future__ import annotations

import io
import os

import numpy as np

from ... import ndarray as nd
from ...base import MXTPUError
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "GloVe", "FastText"]

_REGISTRY = {}


def register(cls):
    """Register an embedding class (parity: embedding.register)."""
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    """Create a registered embedding by name (parity: embedding.create)."""
    cls = _REGISTRY.get(embedding_name.lower())
    if cls is None:
        raise MXTPUError("unknown embedding %r; registered: %s"
                         % (embedding_name, sorted(_REGISTRY)))
    return cls(**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Names of pretrained tables each class knows how to parse.  (The
    reference returns downloadable archives; here the names document the
    expected local-file naming.)"""
    if embedding_name is not None:
        cls = _REGISTRY.get(embedding_name.lower())
        if cls is None:
            raise MXTPUError("unknown embedding %r" % embedding_name)
        return list(cls.pretrained_file_names)
    return {name: list(cls.pretrained_file_names)
            for name, cls in _REGISTRY.items()}


class TokenEmbedding:
    """Base: token → vector lookup table with an unknown-token vector."""

    pretrained_file_names = ()

    def __init__(self, unknown_token="<unk>",
                 init_unknown_vec=None):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec or (lambda s: np.zeros(s))
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec = None  # NDArray (N, dim)

    # -- file loading ----------------------------------------------------
    def _load_embedding_txt(self, path, elem_delim=" ", encoding="utf8"):
        vecs = []
        dim = None
        with io.open(path, "r", encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if lineno == 0 and len(parts) == 2:
                    continue  # fastText header: "<count> <dim>"
                if len(parts) < 2:
                    continue
                token, elems = parts[0], parts[1:]
                if dim is None:
                    dim = len(elems)
                elif len(elems) != dim:
                    raise MXTPUError(
                        "%s:%d: inconsistent vector length %d != %d"
                        % (path, lineno + 1, len(elems), dim))
                if token in self._token_to_idx:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(np.asarray(elems, dtype=np.float32))
        if dim is None:
            raise MXTPUError("no vectors found in %s" % path)
        table = np.empty((len(self._idx_to_token), dim), np.float32)
        table[0] = self._init_unknown_vec((dim,))
        if vecs:
            table[1:] = np.stack(vecs)
        self._idx_to_vec = nd.array(table)

    # -- API -------------------------------------------------------------
    def __len__(self):
        return len(self._idx_to_token)

    @property
    def vec_len(self):
        return int(self._idx_to_vec.shape[1])

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            if t in self._token_to_idx:
                idxs.append(self._token_to_idx[t])
            elif lower_case_backup and t.lower() in self._token_to_idx:
                idxs.append(self._token_to_idx[t.lower()])
            else:
                idxs.append(0)
        out = self._idx_to_vec[np.asarray(idxs)]
        return out[0] if single else out

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        arr = np.array(self._idx_to_vec.asnumpy())  # writable copy
        new = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else np.asarray(new_vectors, np.float32)
        new = new.reshape(len(toks), -1)
        for t, v in zip(toks, new):
            if t not in self._token_to_idx:
                raise MXTPUError("token %r not indexed" % t)
            arr[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(arr)


@register
class GloVe(TokenEmbedding):
    """GloVe text-format table from a local file (parity: text.embedding
    .GloVe minus the download step)."""

    pretrained_file_names = (
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt")

    def __init__(self, pretrained_file_name="glove.6B.50d.txt",
                 embedding_root=None, **kwargs):
        super().__init__(**kwargs)
        path = pretrained_file_name if os.path.isabs(pretrained_file_name) \
            else os.path.join(embedding_root or ".", pretrained_file_name)
        self._load_embedding_txt(path)


@register
class FastText(TokenEmbedding):
    """fastText .vec table (same line format, with a count/dim header)."""

    pretrained_file_names = (
        "wiki.en.vec", "wiki.simple.vec", "wiki.zh.vec", "wiki.fr.vec")

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=None, **kwargs):
        super().__init__(**kwargs)
        path = pretrained_file_name if os.path.isabs(pretrained_file_name) \
            else os.path.join(embedding_root or ".", pretrained_file_name)
        self._load_embedding_txt(path)


class CustomEmbedding(TokenEmbedding):
    """Any local file in `token<delim>v1<delim>...` format (parity:
    text.embedding.CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_txt(pretrained_file_path, elem_delim,
                                 encoding)


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (parity:
    text.embedding.CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(vocabulary, Vocabulary):
            raise MXTPUError("vocabulary must be a text.vocab.Vocabulary")
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._vocab = vocabulary
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = [e.get_vecs_by_tokens(self._idx_to_token).asnumpy()
                 for e in token_embeddings]
        self._idx_to_vec = nd.array(np.concatenate(parts, axis=1))

    @property
    def vocabulary(self):
        return self._vocab
