"""Orbax-backed sharded checkpointing for SPMD training.

The reference's three checkpoint formats (SURVEY §5 checkpoint/resume)
all serialize host-side bytes; `SPMDTrainer.save_states` likewise
gathers optimizer state to host numpy.  That is fine at single-host
scale but is exactly the pattern that breaks at pod scale: gathering a
tp/ep-sharded model through one host serializes the job on one NIC.

This adapter writes the trainer's PARAMETERS + OPTIMIZER STATE + step
count through orbax (the JAX-ecosystem checkpoint library, in-image):
each host writes its own shards (OCDBT), restore re-places leaves onto
the CURRENT mesh sharding — so topology can change between save and
restore, and no full host gather ever happens.

API (checkpoint path must be a fresh/empty directory):

    from mxtpu.contrib import orbax_ckpt
    orbax_ckpt.save_trainer(path, trainer)          # blocking
    orbax_ckpt.restore_trainer(path, trainer)       # onto current mesh

The legacy formats remain for interop; this is the scale path.
"""

from __future__ import annotations

import os
from typing import Any

import jax

__all__ = ["save_trainer", "restore_trainer"]


def _trainer_tree(trainer):
    """The checkpointed pytree: params by name + optimizer states +
    scalar step count (as a host int handled via the metadata leaf)."""
    params = {p.name: p.data()._data
              for p in trainer._diff_params + trainer._aux_params}
    return {
        "params": params,
        "opt_states": tuple(trainer._opt_states),
        "num_update": trainer._num_update,
    }


def save_trainer(path: str, trainer, retry=None) -> None:
    """Write params + optimizer state + step count.  Must run after the
    trainer staged its parameters (one step, or step() bootstrap).

    ``retry``: optional :class:`mxtpu.resilience.RetryPolicy` for
    transient storage failures.  The ``checkpoint.save`` fault-injection
    site fires before orbax touches the path, so injected faults never
    leave a partial checkpoint behind; a real mid-write failure may
    leave one, which orbax refuses to overwrite — retries of that case
    need a fresh path (documented limitation, docs/resilience.md)."""
    from ..resilience.faults import inject as _inject
    import orbax.checkpoint as ocp

    if not trainer._params_sharded:
        raise ValueError(
            "save_trainer: run one trainer.step first so parameters and "
            "optimizer state exist on the mesh")
    path = os.path.abspath(path)

    def attempt():
        _inject("checkpoint.save")
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, _trainer_tree(trainer))

    def manifest():
        # verified-checkpoint weave (docs/guardian.md): record every
        # file's size + CRC32 in a <path>.mxmf sidecar so restore can
        # prove the tree intact before orbax parses it.  Retried
        # separately from the orbax save: re-entering attempt() after
        # the payload landed would fail on the already-existing path.
        # Process 0 ONLY: the orbax save above is collective (every host
        # writes its own shards), but the manifest is one whole-tree CRC
        # pass — running it on every host would re-read the entire
        # multi-host tree num_processes times over shared storage,
        # defeating the no-host-gather point of this path.
        if jax.process_index() != 0:
            return
        from ..resilience import checkpoint as _ckpt
        _ckpt.write_dir_manifest(path)

    if retry is None:
        attempt()
        manifest()
    else:
        retry.call(attempt)
        retry.call(manifest)


def restore_trainer(path: str, trainer) -> None:
    """Restore onto the CURRENT mesh: every leaf is re-placed with the
    trainer's present shardings (topology may differ from save time).
    When a ``.mxmf`` directory manifest exists (written by
    :func:`save_trainer`), the tree is CRC-verified first — damage
    raises a typed :class:`~mxtpu.resilience.CorruptCheckpointError`
    naming the bad member instead of an orbax deserialization error."""
    import orbax.checkpoint as ocp

    from ..resilience import checkpoint as _ckpt

    _ckpt.verify_dir(os.path.abspath(path))
    if not trainer._params_sharded:
        raise ValueError(
            "restore_trainer: run one trainer.step first (or stage "
            "parameters) so target shardings exist")
    path = os.path.abspath(path)
    target = _trainer_tree(trainer)
    # abstract target: shapes/dtypes/shardings of the live tree
    abstract = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
        if isinstance(a, jax.Array) else a, target)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, abstract)

    for p in trainer._diff_params + trainer._aux_params:
        p.data()._rebind(restored["params"][p.name])
    trainer._opt_states = list(restored["opt_states"])
    trainer._num_update = int(restored["num_update"])
