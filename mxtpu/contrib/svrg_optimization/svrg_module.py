"""SVRG (stochastic variance-reduced gradient) training module (parity:
python/mxnet/contrib/svrg_optimization/svrg_module.py + svrg_optimizer.py).

SVRG keeps a snapshot of the parameters taken every `update_freq` epochs
and the FULL-dataset gradient at that snapshot; each minibatch update uses
    g = grad(w) - grad(w_snapshot) + full_grad(w_snapshot)
which is an unbiased, lower-variance gradient estimate.  The reference
implements this as a Module subclass driving two executors plus a special
KVStore optimizer pair (_SVRGOptimizer); here the same algebra runs over
the Module API directly — the snapshot executor is a second Module bound
to shared data shapes.
"""

from __future__ import annotations

import logging

import numpy as np

from ... import ndarray as nd
from ...module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2,
                 logger=logging, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger, **kwargs)
        if update_freq < 1:
            raise ValueError("update_freq must be >= 1")
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               **kwargs)
        self._param_dict = None   # full grad at snapshot, per param
        self._snapshot_epoch = -1

    # -- plumbing shared with the aux (snapshot) module -------------------
    def bind(self, *args, **kwargs):
        super().bind(*args, **kwargs)
        self._mod_aux.bind(*args, **kwargs)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        self._sync_snapshot_params()

    def _sync_snapshot_params(self):
        arg, aux = self.get_params()
        self._mod_aux.set_params({k: v.copy() for k, v in arg.items()},
                                 {k: v.copy() for k, v in aux.items()})

    # -- SVRG specifics ----------------------------------------------------
    @staticmethod
    def _grad_arrays(mod):
        gd = mod._exec.grad_dict
        return {n: gd[n] for n in mod._param_names if gd.get(n) is not None}

    def update_full_grads(self, train_data):
        """Snapshot current params and accumulate the full-dataset
        gradient at the snapshot (parity: SVRGModule.update_full_grads)."""
        self._sync_snapshot_params()
        if hasattr(train_data, "reset"):
            train_data.reset()
        acc = None
        nbatch = 0
        for batch in train_data:
            self._mod_aux.forward_backward(batch)
            grads = self._grad_arrays(self._mod_aux)
            if acc is None:
                acc = {k: g.asnumpy().copy() for k, g in grads.items()}
            else:
                for k, g in grads.items():
                    acc[k] += g.asnumpy()
            nbatch += 1
        if not nbatch:
            raise ValueError("update_full_grads: empty iterator")
        self._param_dict = {k: nd.array(v / nbatch)
                            for k, v in acc.items()}
        if hasattr(train_data, "reset"):
            train_data.reset()

    def update_svrg_gradients(self):
        """Rewrite this module's gradients in place:
        g ← g - g_snapshot(batch) + full_grad_snapshot."""
        if self._param_dict is None:
            return
        cur = self._grad_arrays(self)
        snap = self._grad_arrays(self._mod_aux)
        for name, g in cur.items():
            adj = g.asnumpy() - snap[name].asnumpy() + \
                self._param_dict[name].asnumpy()
            g._rebind(nd.array(adj)._data)

    def forward_backward(self, data_batch):
        super().forward_backward(data_batch)
        if self._param_dict is not None:
            # same minibatch through the snapshot weights
            self._mod_aux.forward_backward(data_batch)
            self.update_svrg_gradients()

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            num_epoch=None, **kwargs):
        """Training loop with the SVRG schedule: refresh the snapshot +
        full gradient every `update_freq` epochs (parity:
        SVRGModule.fit)."""
        if num_epoch is None:
            raise ValueError("num_epoch required")
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                if not (self.binded and self.params_initialized):
                    # zero-epoch fit: bind + init_params + init_optimizer
                    # without running batches (range(begin, num) is empty)
                    super().fit(train_data, eval_data, eval_metric,
                                begin_epoch=epoch, num_epoch=epoch,
                                **kwargs)
                self.update_full_grads(train_data)
            super().fit(train_data, eval_data, eval_metric,
                        begin_epoch=epoch, num_epoch=epoch + 1, **kwargs)
        return self
