"""INT8 post-training quantization (parity: python/mxnet/contrib/
quantization.py — `quantize_model` graph rewrite + naive/entropy
calibration over src/operator/quantization/*).

TPU-native design: quantized FullyConnected/Convolution execute as real
int8 tensor ops with int32 accumulation (`lax.dot_general` /
`conv_general_dilated` with ``preferred_element_type=int32`` — the MXU has
native int8 throughput), then dequantize by the combined scale.  Weights
are stored int8 in the quantized params (the memory win is real); per-layer
input ranges come from calibration exactly like the reference: 'naive'
min/max over calibration batches, or 'entropy' KL-optimal thresholds
(histogram search, quantization/calibrate.cc analogue).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXTPUError, register_op
from .. import ndarray as nd
from ..gluon.nn.basic_layers import Dense as _Dense
from ..ndarray import NDArray

__all__ = ["quantize_model", "quantize_net", "quantize_params",
           "optimal_thresholds", "quantize_weights", "QuantizedDense",
           "pack_int4", "unpack_int4"]

QUANTIZABLE = ("FullyConnected", "Convolution")


# ------------------------------------------------------------ quant ops

def _q_scale(mn, mx):
    """Symmetric int8 scale from a (possibly asymmetric) float range."""
    return jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-8) / 127.0


@register_op("_contrib_quantize_v2", differentiable=False, num_outputs=3)
def quantize_v2(x, min_calib_range=None, max_calib_range=None):
    """fp32 → (int8, min, max) (parity: quantize_v2-inl.h, symmetric
    int8 mode).  Without calib ranges, uses the tensor's own min/max."""
    mn = jnp.min(x) if min_calib_range is None else \
        jnp.asarray(min_calib_range, jnp.float32)
    mx = jnp.max(x) if max_calib_range is None else \
        jnp.asarray(max_calib_range, jnp.float32)
    scale = _q_scale(mn, mx)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, mn, mx


@register_op("_contrib_dequantize_v2", differentiable=False)
def dequantize_v2(q, mn, mx):
    """int8 symmetric dequantize, the inverse of _contrib_quantize_v2
    (the uint8 affine `dequantize` lives in ops/contrib.py)."""
    return q.astype(jnp.float32) * _q_scale(mn, mx)


@register_op("_contrib_quantized_fully_connected", differentiable=False)
def quantized_fully_connected(x, weight, x_min, x_max, w_min, w_max,
                              bias=None, num_hidden=0, no_bias=False,
                              flatten=True):
    """int8 GEMM with int32 accumulation; float bias is added after
    dequantization (simpler than the reference's int32-bias requantize,
    same numerics class)."""
    if flatten and x.ndim > 2:
        x = jnp.reshape(x, (x.shape[0], -1))
    acc = lax.dot_general(x, weight,
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (_q_scale(x_min, x_max) *
                                     _q_scale(w_min, w_max))
    if not no_bias and bias is not None:
        out = out + bias
    return out


@register_op("_contrib_quantized_conv", differentiable=False)
def quantized_conv(x, weight, x_min, x_max, w_min, w_max, bias=None,
                   kernel=(), stride=(), dilate=(), pad=(), num_filter=0,
                   num_group=1, no_bias=False):
    """int8 NCHW convolution, int32 accumulation (cuDNN int8 conv
    analogue — on TPU the MXU takes int8 natively)."""
    ndim = len(kernel) if kernel else x.ndim - 2
    stride = tuple(stride) if stride else (1,) * ndim
    dilate = tuple(dilate) if dilate else (1,) * ndim
    pad = tuple(pad) if pad else (0,) * ndim
    spatial = "DHW"[3 - ndim:]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    acc = lax.conv_general_dilated(
        x, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (_q_scale(x_min, x_max) *
                                     _q_scale(w_min, w_max))
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * ndim)
    return out


# --------------------------------------------- weight-only int8/int4 path
# Decode is HBM-bandwidth-bound: the weights cross HBM once per token,
# so halving (int8) or quartering (int4) their bytes is a direct
# tokens/s multiplier in that regime — and the activations stay float,
# so no calibration data is needed.  The dequantize is FUSED into the
# matmul program: the int8 payload feeds the contraction directly and
# the per-output-channel scale lands in the epilogue (int4 adds
# group-wise scales over the input dim, applied per contraction group).
# A float copy of the weight is never materialized.


def _wq_flatten(x, flatten):
    if flatten and x.ndim > 2:
        return jnp.reshape(x, (x.shape[0], -1))
    return x


@register_op("wq_matmul_i8", differentiable=False)
def wq_matmul_i8(x, qweight, wscale, bias=None, flatten=False,
                 no_bias=False):
    """Weight-only int8 matmul: y = (x · q^T) * s [+ bias] with
    ``qweight`` (O, I) int8 and per-output-channel ``wscale`` (O,).
    The scale distributes over the contraction, so it applies AFTER the
    matmul — the epilogue form XLA fuses — and the accumulation runs in
    fp32 regardless of x's dtype (the serving numerics contract)."""
    x = _wq_flatten(x, flatten)
    prec = lax.Precision.HIGHEST if x.dtype == jnp.float32 else None
    acc = lax.dot_general(
        x.astype(jnp.float32), qweight.astype(jnp.float32),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)
    out = acc * wscale.astype(jnp.float32)
    if not no_bias and bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


@register_op("wq_matmul_i8_q8", differentiable=False, num_outputs=2)
def wq_matmul_i8_q8(x, qweight, wscale, bias=None, head_dim=0,
                    flatten=False, no_bias=False):
    """Weight-only int8 matmul with a FUSED int8-quantize epilogue —
    the int8-weights × int8-KV fast path's projection op: the
    ``wq_matmul_i8`` product is quantized per ``head_dim`` group of the
    output axis straight into cache form, returning ((…, O) int8
    payload, (…, O/head_dim) float32 scales) for a pre-quantized paged
    write (``_paged_cache_write_rows_pre_q8``).  Between the int8
    weights and the int8 cache nothing float-typed crosses an op
    boundary.

    Bit-exactness contract: the epilogue applies the SAME math, in the
    same order, as the quantize-on-write path — ``wq_matmul_i8``'s fp32
    accumulate + scale (+ bias) + x.dtype cast, then ops.tensor's
    ``_q8_quantize`` per head vector — so the stored cache bits are
    identical to projecting float and quantizing at the write
    (tests/test_quantized_serving.py asserts it)."""
    from ..ops.tensor import _q8_quantize

    y = wq_matmul_i8(x, qweight, wscale, bias, flatten=flatten,
                     no_bias=no_bias)
    O = qweight.shape[0]
    hd = int(head_dim) or O
    lead = y.shape[:-1]
    q, s = _q8_quantize(y.reshape(lead + (O // hd, hd)))
    return q.reshape(lead + (O,)), s


@register_op("wq_matmul_i4", differentiable=False)
def wq_matmul_i4(x, qweight, wscale, bias=None, flatten=False,
                 no_bias=False, group_size=0, in_units=0):
    """Weight-only int4 matmul: ``qweight`` (O, I//2) int8 packs two
    nibbles per byte (even input index low, odd high); ``wscale``
    (O, G) holds one scale per output channel per input GROUP of
    ``group_size`` (G = I / group_size).  Unpack is sign-extending
    shift arithmetic in-program; the group scales fold into the
    contraction as einsum('ngi,ogi,og->no')."""
    x = _wq_flatten(x, flatten)
    O = qweight.shape[0]
    I = int(in_units) or qweight.shape[1] * 2
    gs = int(group_size) or I
    # sign-extending nibble unpack: int8 arithmetic shifts
    lo = jnp.right_shift(jnp.left_shift(qweight, 4), 4)
    hi = jnp.right_shift(qweight, 4)
    w = jnp.stack([lo, hi], axis=-1).reshape(O, I).astype(jnp.float32)
    lead = x.shape[:-1]
    xg = x.astype(jnp.float32).reshape(-1, I // gs, gs)
    wg = w.reshape(O, I // gs, gs)
    prec = lax.Precision.HIGHEST if x.dtype == jnp.float32 else None
    out = jnp.einsum("ngi,ogi,og->no", xg, wg,
                     wscale.astype(jnp.float32),
                     preferred_element_type=jnp.float32, precision=prec)
    out = out.reshape(lead + (O,))
    if not no_bias and bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


# contrib ops register AFTER the generated mx.nd / mx.sym namespaces are
# built, so bind the weight-only matmuls in explicitly — QuantizedDense's
# hybrid_forward addresses them as F.<op> under both dispatch modes
def _bind_namespaces():
    from .. import ndarray as _ndm
    from .. import symbol as _symm

    for _n in ("wq_matmul_i8", "wq_matmul_i8_q8", "wq_matmul_i4"):
        if not hasattr(_ndm, _n):
            setattr(_ndm, _n, _ndm._make_op_fn(_n))
        if not hasattr(_symm, _n):
            setattr(_symm, _n, _symm._make_sym_op(_n))


_bind_namespaces()


def pack_int4(q):
    """Pack an int4-valued int8 array (O, I) into (O, I//2) bytes —
    even input index in the low nibble, odd in the high (the
    wq_matmul_i4 layout).  Host-side numpy; runs once at quantize
    time."""
    q = np.asarray(q, np.int8)
    if q.shape[-1] % 2:
        raise MXTPUError("pack_int4 needs an even input dim, got %r"
                         % (q.shape,))
    lo = q[..., 0::2].astype(np.uint8) & 0xF
    hi = q[..., 1::2].astype(np.uint8) & 0xF
    return ((hi << 4) | lo).astype(np.uint8).view(np.int8)


def unpack_int4(packed):
    """Inverse of pack_int4 (tests / inspection)."""
    b = np.asarray(packed, np.int8)
    lo = (b.astype(np.int8) << 4).astype(np.int8) >> 4
    hi = b >> 4
    out = np.stack([lo, hi], axis=-1)
    return out.reshape(b.shape[:-1] + (b.shape[-1] * 2,))


def _i4_group(in_units, group_size):
    """Largest divisor of ``in_units`` <= the requested group size —
    group boundaries must tile the input dim exactly."""
    g = max(1, min(int(group_size), in_units))
    while in_units % g:
        g -= 1
    return g


class QuantizedDense(_Dense):
    """Weight-only quantized Dense: packed int8/int4 weight + scale
    params, forward through the fused wq_matmul ops.  Subclasses Dense
    so :func:`quantize_weights` can swap it into a parent block under
    the attribute-type guard; built from an INITIALIZED Dense.

    The packed ``weight`` parameter keeps the original parameter NAME
    (so existing TP sharding rules — e.g. ``qkv_weight$`` → column
    parallel — apply unchanged); the new ``wscale`` parameter gets an
    exact-name rule appended by quantize_weights."""

    def __init__(self, units, in_units, bits=8, group_size=64,
                 use_bias=True, flatten=False, activation=None,
                 dtype="float32", **kwargs):
        from ..gluon.block import HybridBlock
        from ..gluon.nn.basic_layers import Activation

        HybridBlock.__init__(self, **kwargs)
        if bits not in (8, 4):
            raise MXTPUError("weight-only bits must be 8 or 4, got %r"
                             % (bits,))
        if bits == 4 and in_units % 2:
            raise MXTPUError(
                "int4 packing needs an even input dim, got %d" % in_units)
        self._units = units
        self._in_units = in_units
        self._flatten = flatten
        self._bits = bits
        self._gs = _i4_group(in_units, group_size) if bits == 4 else 0
        with self.name_scope():
            wshape = ((units, in_units) if bits == 8
                      else (units, in_units // 2))
            sshape = ((units,) if bits == 8
                      else (units, in_units // self._gs))
            self.weight = self.params.get(
                "weight", shape=wshape, dtype="int8", grad_req="null",
                init="zeros")
            self.wscale = self.params.get(
                "wscale", shape=sshape, dtype="float32", grad_req="null",
                init="ones")
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype, init="zeros")
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x, *args):
        pass  # shapes are concrete at construction

    def hybrid_forward(self, F, x, weight=None, wscale=None, bias=None):
        if self._bits == 8:
            out = F.wq_matmul_i8(x, weight, wscale, bias,
                                 flatten=self._flatten,
                                 no_bias=bias is None)
        else:
            out = F.wq_matmul_i4(x, weight, wscale, bias,
                                 flatten=self._flatten,
                                 no_bias=bias is None,
                                 group_size=self._gs,
                                 in_units=self._in_units)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return ("%s(%d -> %d, int%d%s)"
                % (type(self).__name__, self._in_units, self._units,
                   self._bits,
                   ", gs=%d" % self._gs if self._bits == 4 else ""))


def _quantize_dense(dense, bits, group_size):
    """Build the QuantizedDense replacement for one initialized Dense."""
    from .. import ndarray as _nd

    w = dense.weight.data().asnumpy().astype(np.float32)
    O, I = w.shape
    act = dense.act._act_type if dense.act is not None else None
    qd = QuantizedDense(O, I, bits=bits, group_size=group_size,
                        use_bias=dense.bias is not None,
                        flatten=dense._flatten, activation=act,
                        prefix=dense.prefix)
    qd.initialize()
    if bits == 8:
        s = np.maximum(np.abs(w).max(axis=1), 1e-8) / 127.0    # (O,)
        q = np.clip(np.round(w / s[:, None]), -127, 127).astype(np.int8)
        qd.weight.set_data(_nd.array(q))
        qd.wscale.set_data(_nd.array(s.astype(np.float32)))
    else:
        gs = qd._gs
        wg = w.reshape(O, I // gs, gs)
        s = np.maximum(np.abs(wg).max(axis=2), 1e-8) / 7.0     # (O, G)
        q = np.clip(np.round(wg / s[..., None]), -7, 7).astype(
            np.int8).reshape(O, I)
        qd.weight.set_data(_nd.array(pack_int4(q)))
        qd.wscale.set_data(_nd.array(s.astype(np.float32)))
    if dense.bias is not None:
        qd.bias.set_data(dense.bias.data())
    return qd


def quantize_weights(block, bits=8, group_size=64, rules=None,
                     exclude=()):
    """Rewrite every initialized ``nn.Dense`` under ``block`` —
    attention/FFN projections, lm heads — to a packed-weight
    :class:`QuantizedDense` (weight-only int8 or int4; activations and
    the KV cache are untouched — pair with ``cache_dtype="int8"`` for
    the full quantized serving path, docs/inference.md).

    ``rules``: the block's TP ShardingRules; returns a NEW ShardingRules
    extending them with exact-name rules for each ``wscale`` parameter
    (an int8 scale shards with its weight's output-channel axis; int4
    group scales shard the output-channel axis and replicate the group
    axis), so the result drops into ``ShardedDecoder`` under tensor
    parallelism unchanged.  ``exclude``: parameter-name substrings to
    leave in float (e.g. ``("lm_head",)``).

    Embedding weights (and a tied lm head, which reads the embedding)
    are never touched.  Raises on uninitialized parameters — quantize
    after ``initialize()`` + shape resolution (one forward if shapes
    were deferred)."""
    import re as _re

    from ..parallel.sharding import PartitionSpec as _P, ShardingRules

    if bits not in (8, 4):
        raise MXTPUError("weight-only bits must be 8 or 4, got %r"
                         % (bits,))
    base = rules.iter_rules() if rules is not None else []
    out_rules = ShardingRules(list(base))
    quantized = []

    def walk(parent):
        for name, child in list(parent._children.items()):
            if type(child) is _Dense and not any(
                    token in child.weight.name for token in exclude):
                if child.weight._data is None and not \
                        child.weight._deferred_init:
                    raise MXTPUError(
                        "quantize_weights: parameter %r is uninitialized"
                        " — call initialize() first" % child.weight.name)
                if child.weight._deferred_init or 0 in child.weight.shape:
                    raise MXTPUError(
                        "quantize_weights: parameter %r has a deferred "
                        "shape — run one forward pass first"
                        % child.weight.name)
                qd = _quantize_dense(child, bits, group_size)
                if getattr(parent, name, None) is child:
                    setattr(parent, name, qd)   # re-registers the child
                else:
                    parent._children[name] = qd
                wspec = tuple(rules.spec_for(child.weight.name, 2)) \
                    if rules is not None else ()
                col = wspec[0] if wspec else None
                sspec = _P(col) if bits == 8 else _P(col, None)
                out_rules.add(_re.escape(qd.wscale.name) + "$", sspec)
                quantized.append(child.weight.name)
            else:
                walk(child)

    walk(block)
    if not quantized:
        raise MXTPUError("quantize_weights: no initialized Dense layers "
                         "found under %r" % (block,))
    out_rules.quantized_params = tuple(quantized)
    return out_rules


# ----------------------------------------------------------- calibration

def optimal_thresholds(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| from a symmetric histogram
    (parity: _get_optimal_threshold / the TensorRT-style KL search in
    quantization/calibrate).  P is the windowed histogram with clipped
    outlier mass folded into its edge bins; Q is the window re-binned to
    num_quantized_bins WITHOUT the outlier mass — so clipping real mass
    shows up as P-edge >> Q-edge divergence, and over-wide windows pay
    through coarse re-binning.  Returns the |edge| minimizing KL(P||Q)."""
    num_bins = len(hist)
    zero = num_bins // 2
    best_kl, best_t = np.inf, abs(hist_edges[-1])
    for i in range(num_quantized_bins // 2, zero + 1):
        lo, hi = zero - i, zero + i
        sliced = hist[lo:hi].astype(np.float64)
        p = sliced.copy()
        p[0] += hist[:lo].sum()
        p[-1] += hist[hi:].sum()
        if p.sum() == 0:
            continue
        # re-bin the (outlier-free) window to the quantized grid, then
        # expand back over the nonzero support of the window
        factor = len(sliced) / num_quantized_bins
        q = np.zeros_like(sliced)
        for j in range(num_quantized_bins):
            a = int(np.floor(j * factor))
            b = min(int(np.ceil((j + 1) * factor)), len(sliced))
            chunk = sliced[a:b]
            cnt = (chunk > 0).sum()
            if cnt:
                q[a:b][chunk > 0] = chunk.sum() / cnt
        p_n = p / p.sum()
        if q.sum() == 0:
            continue
        q_n = q / q.sum()
        support = p_n > 0
        q_s = np.where(q_n[support] > 0, q_n[support], 1e-10)
        kl = float(np.sum(p_n[support] * np.log(p_n[support] / q_s)))
        if kl < best_kl:
            best_kl = kl
            best_t = abs(hist_edges[hi])
    return best_t


class _Collector:
    """Per-layer input statistics over calibration batches."""

    def __init__(self, mode, num_bins=2048):
        self.mode = mode
        self.num_bins = num_bins
        self.minmax = {}
        self.hists = {}

    def update(self, name, arr):
        arr = np.asarray(arr)
        mn, mx = float(arr.min()), float(arr.max())
        if name in self.minmax:
            omn, omx = self.minmax[name]
            self.minmax[name] = (min(mn, omn), max(mx, omx))
        else:
            self.minmax[name] = (mn, mx)
        if self.mode == "entropy":
            th = max(abs(mn), abs(mx), 1e-8)
            hist, edges = np.histogram(arr, bins=self.num_bins,
                                       range=(-th, th))
            self.hists.setdefault(name, []).append((hist, edges))

    def ranges(self):
        out = {}
        for name, (mn, mx) in self.minmax.items():
            if self.mode == "entropy":
                # merge per-batch histograms onto one grid spanning the
                # global range (midpoint re-binning), then KL-search
                th = max(abs(mn), abs(mx), 1e-8)
                edges = np.linspace(-th, th, self.num_bins + 1)
                grid = np.zeros(self.num_bins, np.int64)
                for h, e in self.hists[name]:
                    mids = (e[:-1] + e[1:]) / 2
                    idx = np.clip(np.searchsorted(edges, mids) - 1, 0,
                                  self.num_bins - 1)
                    np.add.at(grid, idx, h)
                t = optimal_thresholds(grid, edges)
                out[name] = (-t, t)
            else:
                out[name] = (mn, mx)
        return out


# --------------------------------------------------------- graph rewrite

def quantize_params(qsym, params):
    """int8-quantize the weights referenced by a quantized symbol
    (parity: quantize_params)."""
    out = {}
    for name in set(qsym.list_arguments()) | \
            set(qsym.list_auxiliary_states()):
        if name.endswith("_quantized"):
            src = name[:-len("_quantized")]
            w = params[src].asnumpy()
            t = max(abs(w.min()), abs(w.max()), 1e-8)
            scale = t / 127.0
            out[name] = nd.array(
                np.clip(np.round(w / scale), -127, 127).astype(np.int8))
            out[src + "_qmin"] = nd.array(np.float32(-t))
            out[src + "_qmax"] = nd.array(np.float32(t))
        elif name in params:
            out[name] = params[name]
    return out


def _rebuild_quantized(sym, ranges, excluded):
    """Topo-rebuild the graph, swapping quantizable nodes onto the int8
    ops with calibrated input ranges."""
    from ..symbol import Symbol, Variable

    memo = {}

    def rebuild(s):
        node = s._node
        if id(node) in memo:
            return memo[id(node)][s._index] if node.num_outputs > 1 \
                else memo[id(node)]
        if node.op is None:
            out = s
            memo[id(node)] = out
            return out
        new_inputs = [rebuild(i) for i in node.inputs]
        if node.op in QUANTIZABLE and node.name not in excluded and \
                node.name in ranges:
            mn, mx = ranges[node.name]
            data = new_inputs[0]
            wname = node.inputs[1].name
            w_q = Variable(wname + "_quantized")
            w_mn = Variable(wname + "_qmin")
            w_mx = Variable(wname + "_qmax")
            no_bias = node.kwargs.get("no_bias", False)
            bias = None if no_bias or len(new_inputs) < 3 else new_inputs[2]
            calib_kw = {} if mn is None else dict(
                min_calib_range=float(mn), max_calib_range=float(mx))
            q_data = Symbol._create(
                "_contrib_quantize_v2", None, [data], calib_kw,
                name=node.name + "_quantize")
            q_data._node.num_outputs = 3
            qop = ("_contrib_quantized_fully_connected"
                   if node.op == "FullyConnected"
                   else "_contrib_quantized_conv")
            kwargs = dict(node.kwargs)
            for junk in ("cudnn_tune", "cudnn_off", "workspace", "layout"):
                kwargs.pop(junk, None)
            ins = [q_data[0], w_q, q_data[1], q_data[2], w_mn, w_mx]
            if bias is not None:
                ins.append(bias)  # trailing optional bias slot
            else:
                kwargs["no_bias"] = True
            out = Symbol._create(qop, None, ins, kwargs,
                                 name=node.name + "_quantized")
        else:
            args = []
            it = iter(new_inputs)
            for slot in node.arg_layout:
                args.append(next(it) if slot is None else slot)
            for extra in it:
                args.append(extra)
            out = Symbol._create(node.op, None, args, dict(node.kwargs),
                                 name=node.name)
            out._node.num_outputs = node.num_outputs
            out._node.attrs.update(node.attrs)
        memo[id(node)] = out
        return out if node.num_outputs == 1 else out[s._index]

    roots = [rebuild(Symbol(n, 0)) for n in sym._roots()]
    if len(roots) == 1:
        return roots[0]
    from ..symbol import Group
    return Group(roots)


def quantize_model(sym, arg_params, aux_params=None, data_names=("data",),
                   excluded_sym_names=(), calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", ctx=None, logger=None):
    """Quantize a model (parity: mx.contrib.quantization.quantize_model).

    Returns (qsym, qarg_params, aux_params).  calib_data: iterable of
    batches (dict name→NDArray, or single-array batches for one data
    input) used to calibrate input ranges of quantized layers; with
    calib_mode='none' ranges are computed at runtime per batch.
    """
    if quantized_dtype != "int8":
        raise MXTPUError("only int8 quantization is supported")
    aux_params = aux_params or {}
    excluded = set(excluded_sym_names)

    targets = [n for n in sym._topo()
               if n.op in QUANTIZABLE and n.name not in excluded]
    if not targets:
        raise MXTPUError("quantize_model: nothing to quantize")

    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXTPUError("calib_mode=%r needs calib_data" % calib_mode)
        ranges = _calibrate(sym, arg_params, aux_params, data_names,
                            targets, calib_data, calib_mode,
                            num_calib_examples)
    elif calib_mode == "none":
        ranges = {n.name: (None, None) for n in targets}
    else:
        raise MXTPUError("unknown calib_mode %r" % calib_mode)

    qsym = _rebuild_quantized(sym, ranges, excluded)
    params = dict(arg_params)
    params.update(aux_params)
    qarg = quantize_params(qsym, params)
    qaux = {k: v for k, v in aux_params.items()
            if k in set(qsym.list_auxiliary_states())}
    return qsym, qarg, qaux


def _calibrate(sym, arg_params, aux_params, data_names, targets,
               calib_data, mode, num_examples):
    """Run fp32 forwards over calib batches, collecting each quantizable
    node's INPUT activation stats (the tensor that will be quantized)."""
    from ..symbol import Group
    from ..context import cpu

    taps = [t.inputs[0] for t in targets]
    tap_sym = Group(list(taps))
    collector = _Collector(mode)
    seen = 0
    for batch in calib_data:
        if not isinstance(batch, dict):
            batch = {data_names[0]: batch}
        args = {k: (v if isinstance(v, NDArray) else nd.array(v))
                for k, v in batch.items()}
        args.update(arg_params)
        arg_names = set(tap_sym.list_arguments())
        aux = dict(aux_params)
        ex = tap_sym.bind(cpu(),
                          {k: v for k, v in args.items()
                           if k in arg_names},
                          aux_states=aux)
        outs = ex.forward()
        for t, out in zip(targets, outs[:len(targets)]):
            collector.update(t.name, out.asnumpy())
        seen += next(iter(batch.values())).shape[0]
        if num_examples and seen >= num_examples:
            break
    return collector.ranges()


def quantize_net(network, quantized_dtype="int8", exclude_layers=(),
                 calib_data=None, calib_mode="naive",
                 num_calib_examples=None, data_names=("data",),
                 ctx=None, logger=None):
    """Quantize a Gluon HybridBlock into an int8 SymbolBlock (parity:
    mx.contrib.quantization.quantize_net — trace the block to a symbol,
    run quantize_model, wrap the result for imperative use)."""
    from ..gluon.block import SymbolBlock
    from ..symbol import trace_block, var

    sym = trace_block(network, input_names=data_names)
    all_params = {}
    for name, p in network.collect_params().items():
        if p._data is None:
            raise MXTPUError(
                "quantize_net: parameter %r is uninitialized — run a "
                "forward pass first" % name)
        all_params[name] = p.data()
    # classify by the TRACED GRAPH's own view, not grad_req: traced
    # Parameter.var()s are plain Variables (no __aux__), so BatchNorm
    # running stats land in list_arguments() and must be bound as args
    # during calibration
    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params = {k: v for k, v in all_params.items() if k in arg_names}
    aux_params = {k: v for k, v in all_params.items() if k in aux_names}

    qsym, qargs, qaux = quantize_model(
        sym, arg_params, aux_params, data_names=data_names,
        excluded_sym_names=exclude_layers, calib_mode=calib_mode,
        calib_data=calib_data, num_calib_examples=num_calib_examples,
        quantized_dtype=quantized_dtype)

    params = {k: v for k, v in qargs.items()}
    params.update(qaux)
    return SymbolBlock(qsym, [var(n) for n in data_names], params=params)
