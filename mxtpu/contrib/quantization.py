"""INT8 post-training quantization (parity: python/mxnet/contrib/
quantization.py — `quantize_model` graph rewrite + naive/entropy
calibration over src/operator/quantization/*).

TPU-native design: quantized FullyConnected/Convolution execute as real
int8 tensor ops with int32 accumulation (`lax.dot_general` /
`conv_general_dilated` with ``preferred_element_type=int32`` — the MXU has
native int8 throughput), then dequantize by the combined scale.  Weights
are stored int8 in the quantized params (the memory win is real); per-layer
input ranges come from calibration exactly like the reference: 'naive'
min/max over calibration batches, or 'entropy' KL-optimal thresholds
(histogram search, quantization/calibrate.cc analogue).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXTPUError, register_op
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["quantize_model", "quantize_net", "quantize_params",
           "optimal_thresholds"]

QUANTIZABLE = ("FullyConnected", "Convolution")


# ------------------------------------------------------------ quant ops

def _q_scale(mn, mx):
    """Symmetric int8 scale from a (possibly asymmetric) float range."""
    return jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-8) / 127.0


@register_op("_contrib_quantize_v2", differentiable=False, num_outputs=3)
def quantize_v2(x, min_calib_range=None, max_calib_range=None):
    """fp32 → (int8, min, max) (parity: quantize_v2-inl.h, symmetric
    int8 mode).  Without calib ranges, uses the tensor's own min/max."""
    mn = jnp.min(x) if min_calib_range is None else \
        jnp.asarray(min_calib_range, jnp.float32)
    mx = jnp.max(x) if max_calib_range is None else \
        jnp.asarray(max_calib_range, jnp.float32)
    scale = _q_scale(mn, mx)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, mn, mx


@register_op("_contrib_dequantize_v2", differentiable=False)
def dequantize_v2(q, mn, mx):
    """int8 symmetric dequantize, the inverse of _contrib_quantize_v2
    (the uint8 affine `dequantize` lives in ops/contrib.py)."""
    return q.astype(jnp.float32) * _q_scale(mn, mx)


@register_op("_contrib_quantized_fully_connected", differentiable=False)
def quantized_fully_connected(x, weight, x_min, x_max, w_min, w_max,
                              bias=None, num_hidden=0, no_bias=False,
                              flatten=True):
    """int8 GEMM with int32 accumulation; float bias is added after
    dequantization (simpler than the reference's int32-bias requantize,
    same numerics class)."""
    if flatten and x.ndim > 2:
        x = jnp.reshape(x, (x.shape[0], -1))
    acc = lax.dot_general(x, weight,
                          (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (_q_scale(x_min, x_max) *
                                     _q_scale(w_min, w_max))
    if not no_bias and bias is not None:
        out = out + bias
    return out


@register_op("_contrib_quantized_conv", differentiable=False)
def quantized_conv(x, weight, x_min, x_max, w_min, w_max, bias=None,
                   kernel=(), stride=(), dilate=(), pad=(), num_filter=0,
                   num_group=1, no_bias=False):
    """int8 NCHW convolution, int32 accumulation (cuDNN int8 conv
    analogue — on TPU the MXU takes int8 natively)."""
    ndim = len(kernel) if kernel else x.ndim - 2
    stride = tuple(stride) if stride else (1,) * ndim
    dilate = tuple(dilate) if dilate else (1,) * ndim
    pad = tuple(pad) if pad else (0,) * ndim
    spatial = "DHW"[3 - ndim:]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    acc = lax.conv_general_dilated(
        x, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (_q_scale(x_min, x_max) *
                                     _q_scale(w_min, w_max))
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * ndim)
    return out


# ----------------------------------------------------------- calibration

def optimal_thresholds(hist, hist_edges, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| from a symmetric histogram
    (parity: _get_optimal_threshold / the TensorRT-style KL search in
    quantization/calibrate).  P is the windowed histogram with clipped
    outlier mass folded into its edge bins; Q is the window re-binned to
    num_quantized_bins WITHOUT the outlier mass — so clipping real mass
    shows up as P-edge >> Q-edge divergence, and over-wide windows pay
    through coarse re-binning.  Returns the |edge| minimizing KL(P||Q)."""
    num_bins = len(hist)
    zero = num_bins // 2
    best_kl, best_t = np.inf, abs(hist_edges[-1])
    for i in range(num_quantized_bins // 2, zero + 1):
        lo, hi = zero - i, zero + i
        sliced = hist[lo:hi].astype(np.float64)
        p = sliced.copy()
        p[0] += hist[:lo].sum()
        p[-1] += hist[hi:].sum()
        if p.sum() == 0:
            continue
        # re-bin the (outlier-free) window to the quantized grid, then
        # expand back over the nonzero support of the window
        factor = len(sliced) / num_quantized_bins
        q = np.zeros_like(sliced)
        for j in range(num_quantized_bins):
            a = int(np.floor(j * factor))
            b = min(int(np.ceil((j + 1) * factor)), len(sliced))
            chunk = sliced[a:b]
            cnt = (chunk > 0).sum()
            if cnt:
                q[a:b][chunk > 0] = chunk.sum() / cnt
        p_n = p / p.sum()
        if q.sum() == 0:
            continue
        q_n = q / q.sum()
        support = p_n > 0
        q_s = np.where(q_n[support] > 0, q_n[support], 1e-10)
        kl = float(np.sum(p_n[support] * np.log(p_n[support] / q_s)))
        if kl < best_kl:
            best_kl = kl
            best_t = abs(hist_edges[hi])
    return best_t


class _Collector:
    """Per-layer input statistics over calibration batches."""

    def __init__(self, mode, num_bins=2048):
        self.mode = mode
        self.num_bins = num_bins
        self.minmax = {}
        self.hists = {}

    def update(self, name, arr):
        arr = np.asarray(arr)
        mn, mx = float(arr.min()), float(arr.max())
        if name in self.minmax:
            omn, omx = self.minmax[name]
            self.minmax[name] = (min(mn, omn), max(mx, omx))
        else:
            self.minmax[name] = (mn, mx)
        if self.mode == "entropy":
            th = max(abs(mn), abs(mx), 1e-8)
            hist, edges = np.histogram(arr, bins=self.num_bins,
                                       range=(-th, th))
            self.hists.setdefault(name, []).append((hist, edges))

    def ranges(self):
        out = {}
        for name, (mn, mx) in self.minmax.items():
            if self.mode == "entropy":
                # merge per-batch histograms onto one grid spanning the
                # global range (midpoint re-binning), then KL-search
                th = max(abs(mn), abs(mx), 1e-8)
                edges = np.linspace(-th, th, self.num_bins + 1)
                grid = np.zeros(self.num_bins, np.int64)
                for h, e in self.hists[name]:
                    mids = (e[:-1] + e[1:]) / 2
                    idx = np.clip(np.searchsorted(edges, mids) - 1, 0,
                                  self.num_bins - 1)
                    np.add.at(grid, idx, h)
                t = optimal_thresholds(grid, edges)
                out[name] = (-t, t)
            else:
                out[name] = (mn, mx)
        return out


# --------------------------------------------------------- graph rewrite

def quantize_params(qsym, params):
    """int8-quantize the weights referenced by a quantized symbol
    (parity: quantize_params)."""
    out = {}
    for name in set(qsym.list_arguments()) | \
            set(qsym.list_auxiliary_states()):
        if name.endswith("_quantized"):
            src = name[:-len("_quantized")]
            w = params[src].asnumpy()
            t = max(abs(w.min()), abs(w.max()), 1e-8)
            scale = t / 127.0
            out[name] = nd.array(
                np.clip(np.round(w / scale), -127, 127).astype(np.int8))
            out[src + "_qmin"] = nd.array(np.float32(-t))
            out[src + "_qmax"] = nd.array(np.float32(t))
        elif name in params:
            out[name] = params[name]
    return out


def _rebuild_quantized(sym, ranges, excluded):
    """Topo-rebuild the graph, swapping quantizable nodes onto the int8
    ops with calibrated input ranges."""
    from ..symbol import Symbol, Variable

    memo = {}

    def rebuild(s):
        node = s._node
        if id(node) in memo:
            return memo[id(node)][s._index] if node.num_outputs > 1 \
                else memo[id(node)]
        if node.op is None:
            out = s
            memo[id(node)] = out
            return out
        new_inputs = [rebuild(i) for i in node.inputs]
        if node.op in QUANTIZABLE and node.name not in excluded and \
                node.name in ranges:
            mn, mx = ranges[node.name]
            data = new_inputs[0]
            wname = node.inputs[1].name
            w_q = Variable(wname + "_quantized")
            w_mn = Variable(wname + "_qmin")
            w_mx = Variable(wname + "_qmax")
            no_bias = node.kwargs.get("no_bias", False)
            bias = None if no_bias or len(new_inputs) < 3 else new_inputs[2]
            calib_kw = {} if mn is None else dict(
                min_calib_range=float(mn), max_calib_range=float(mx))
            q_data = Symbol._create(
                "_contrib_quantize_v2", None, [data], calib_kw,
                name=node.name + "_quantize")
            q_data._node.num_outputs = 3
            qop = ("_contrib_quantized_fully_connected"
                   if node.op == "FullyConnected"
                   else "_contrib_quantized_conv")
            kwargs = dict(node.kwargs)
            for junk in ("cudnn_tune", "cudnn_off", "workspace", "layout"):
                kwargs.pop(junk, None)
            ins = [q_data[0], w_q, q_data[1], q_data[2], w_mn, w_mx]
            if bias is not None:
                ins.append(bias)  # trailing optional bias slot
            else:
                kwargs["no_bias"] = True
            out = Symbol._create(qop, None, ins, kwargs,
                                 name=node.name + "_quantized")
        else:
            args = []
            it = iter(new_inputs)
            for slot in node.arg_layout:
                args.append(next(it) if slot is None else slot)
            for extra in it:
                args.append(extra)
            out = Symbol._create(node.op, None, args, dict(node.kwargs),
                                 name=node.name)
            out._node.num_outputs = node.num_outputs
            out._node.attrs.update(node.attrs)
        memo[id(node)] = out
        return out if node.num_outputs == 1 else out[s._index]

    roots = [rebuild(Symbol(n, 0)) for n in sym._roots()]
    if len(roots) == 1:
        return roots[0]
    from ..symbol import Group
    return Group(roots)


def quantize_model(sym, arg_params, aux_params=None, data_names=("data",),
                   excluded_sym_names=(), calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", ctx=None, logger=None):
    """Quantize a model (parity: mx.contrib.quantization.quantize_model).

    Returns (qsym, qarg_params, aux_params).  calib_data: iterable of
    batches (dict name→NDArray, or single-array batches for one data
    input) used to calibrate input ranges of quantized layers; with
    calib_mode='none' ranges are computed at runtime per batch.
    """
    if quantized_dtype != "int8":
        raise MXTPUError("only int8 quantization is supported")
    aux_params = aux_params or {}
    excluded = set(excluded_sym_names)

    targets = [n for n in sym._topo()
               if n.op in QUANTIZABLE and n.name not in excluded]
    if not targets:
        raise MXTPUError("quantize_model: nothing to quantize")

    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXTPUError("calib_mode=%r needs calib_data" % calib_mode)
        ranges = _calibrate(sym, arg_params, aux_params, data_names,
                            targets, calib_data, calib_mode,
                            num_calib_examples)
    elif calib_mode == "none":
        ranges = {n.name: (None, None) for n in targets}
    else:
        raise MXTPUError("unknown calib_mode %r" % calib_mode)

    qsym = _rebuild_quantized(sym, ranges, excluded)
    params = dict(arg_params)
    params.update(aux_params)
    qarg = quantize_params(qsym, params)
    qaux = {k: v for k, v in aux_params.items()
            if k in set(qsym.list_auxiliary_states())}
    return qsym, qarg, qaux


def _calibrate(sym, arg_params, aux_params, data_names, targets,
               calib_data, mode, num_examples):
    """Run fp32 forwards over calib batches, collecting each quantizable
    node's INPUT activation stats (the tensor that will be quantized)."""
    from ..symbol import Group
    from ..context import cpu

    taps = [t.inputs[0] for t in targets]
    tap_sym = Group(list(taps))
    collector = _Collector(mode)
    seen = 0
    for batch in calib_data:
        if not isinstance(batch, dict):
            batch = {data_names[0]: batch}
        args = {k: (v if isinstance(v, NDArray) else nd.array(v))
                for k, v in batch.items()}
        args.update(arg_params)
        arg_names = set(tap_sym.list_arguments())
        aux = dict(aux_params)
        ex = tap_sym.bind(cpu(),
                          {k: v for k, v in args.items()
                           if k in arg_names},
                          aux_states=aux)
        outs = ex.forward()
        for t, out in zip(targets, outs[:len(targets)]):
            collector.update(t.name, out.asnumpy())
        seen += next(iter(batch.values())).shape[0]
        if num_examples and seen >= num_examples:
            break
    return collector.ranges()


def quantize_net(network, quantized_dtype="int8", exclude_layers=(),
                 calib_data=None, calib_mode="naive",
                 num_calib_examples=None, data_names=("data",),
                 ctx=None, logger=None):
    """Quantize a Gluon HybridBlock into an int8 SymbolBlock (parity:
    mx.contrib.quantization.quantize_net — trace the block to a symbol,
    run quantize_model, wrap the result for imperative use)."""
    from ..gluon.block import SymbolBlock
    from ..symbol import trace_block, var

    sym = trace_block(network, input_names=data_names)
    all_params = {}
    for name, p in network.collect_params().items():
        if p._data is None:
            raise MXTPUError(
                "quantize_net: parameter %r is uninitialized — run a "
                "forward pass first" % name)
        all_params[name] = p.data()
    # classify by the TRACED GRAPH's own view, not grad_req: traced
    # Parameter.var()s are plain Variables (no __aux__), so BatchNorm
    # running stats land in list_arguments() and must be bound as args
    # during calibration
    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params = {k: v for k, v in all_params.items() if k in arg_names}
    aux_params = {k: v for k, v in all_params.items() if k in aux_names}

    qsym, qargs, qaux = quantize_model(
        sym, arg_params, aux_params, data_names=data_names,
        excluded_sym_names=exclude_layers, calib_mode=calib_mode,
        calib_data=calib_data, num_calib_examples=num_calib_examples,
        quantized_dtype=quantized_dtype)

    params = {k: v for k, v in qargs.items()}
    params.update(qaux)
    return SymbolBlock(qsym, [var(n) for n in data_names], params=params)
