"""RecordIO (parity: python/mxnet/recordio.py + dmlc-core recordio format).

Pure-Python implementation of the dmlc RecordIO container so .rec/.idx
datasets packed for the reference (tools/im2rec) read unchanged. The format:
each record is ``magic(4B) | lrec(4B) | payload | pad-to-4``, where lrec's
upper 3 bits are a continuation flag and lower 29 bits the payload length.
Payloads containing the magic are escaped by splitting into multi-part
records (cflag 1..3), mirroring dmlc-core's recordio writer.
"""

import collections
import os
import struct

import numpy as onp

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "unpack_img", "pack_img"]

_MAGIC = 0xced7230a
_MAGIC_BYTES = struct.pack("<I", _MAGIC)


def _lrec(cflag, length):
    return (cflag << 29) | length


def _dec_lrec(lrec):
    return lrec >> 29, lrec & ((1 << 29) - 1)


class MXRecordIO:
    """Sequential record reader/writer."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.is_open = False
        self._pid = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True
        self._pid = os.getpid()

    def _check_pid(self):
        """Reopen after fork: a DataLoader fork-worker inherits the parent's
        fd (shared file offset) — concurrent seeks would race. Each process
        gets its own handle instead."""
        if self._pid != os.getpid():
            self.record = open(self.uri, "rb")
            self._pid = os.getpid()

    def close(self):
        if self.is_open:
            self.record.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        """Reopen on unpickle (DataLoader worker fork support)."""
        d = dict(self.__dict__)
        d["record"] = None
        d["is_open"] = False
        d["_lock"] = None
        return d

    def __setstate__(self, d):
        import threading
        self.__dict__.update(d)
        if "_lock" in d:
            self._lock = threading.Lock()
        if self.flag == "r":
            self.open()

    def write(self, buf):
        assert self.writable
        # escape embedded magics by splitting the record
        pieces = []
        start = 0
        while True:
            idx = buf.find(_MAGIC_BYTES, start)
            if idx == -1:
                pieces.append(buf[start:])
                break
            pieces.append(buf[start:idx])
            start = idx + 4
        n = len(pieces)
        for i, piece in enumerate(pieces):
            if n == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == n - 1:
                cflag = 3
            else:
                cflag = 2
            self.record.write(_MAGIC_BYTES)
            self.record.write(struct.pack("<I", _lrec(cflag, len(piece))))
            self.record.write(piece)
            pad = (4 - len(piece) % 4) % 4
            if pad:
                self.record.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        self._check_pid()
        out = []
        while True:
            header = self.record.read(8)
            if len(header) < 8:
                return None if not out else b"".join(out)
            magic, lrec = struct.unpack("<II", header)
            assert magic == _MAGIC, "invalid record magic"
            cflag, length = _dec_lrec(lrec)
            data = self.record.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.record.read(pad)
            if cflag == 0:
                return data
            out.append(data)
            if cflag == 3:
                return _MAGIC_BYTES.join(out)

    def tell(self):
        return self.record.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with an .idx sidecar (key\\toffset)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        import threading
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self._lock = threading.Lock()
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (str(key), self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self._check_pid()
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        # seek+read must be atomic when threads share this reader
        # (DataLoader thread_pool=True)
        with self._lock:
            self.seek(idx)
            return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# IRHeader: flag(uint32), label(float32), id(uint64), id2(uint64);
# flag>0 means `flag` extra float labels follow the header.
IRHeader = collections.namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        label = header.label
        header = header._replace(flag=0)
        payload = b""
    else:
        label = onp.asarray(header.label, dtype="float32")
        header = header._replace(flag=label.size, label=0)
        payload = label.tobytes()
    return struct.pack(_IR_FORMAT, *header) + payload + s


def unpack(s):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = onp.frombuffer(s[:header.flag * 4], dtype="float32")
        s = s[header.flag * 4:]
        header = header._replace(label=label)
    return header, s


def unpack_img(s, iscolor=1):
    from . import image
    header, s = unpack(s)
    return header, image.imdecode(s, iscolor).asnumpy()


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    import cv2
    if img_fmt.lower() in (".jpg", ".jpeg"):
        params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt.lower() == ".png":
        params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    else:
        params = []
    ret, buf = cv2.imencode(img_fmt, img, params)
    assert ret, "failed to encode image"
    return pack(header, buf.tobytes())
