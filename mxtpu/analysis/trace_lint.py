"""trace_lint: AST lint for host-sync / retrace hazards in traced code.

Inside a jit/vmap/scan trace, touching concrete values breaks or silently
de-optimizes: ``.item()`` / ``.asnumpy()`` force a device→host sync (and
raise ConcretizationTypeError under jit), ``np.asarray`` on a tracer
fails, ``float()/int()/bool()`` concretize, and Python ``if``/``while``
on array values either raises or bakes the branch into the compiled
program (a retrace per distinct value).  The reference never had this
hazard class — imperative MXNet synced eagerly everywhere — but a
TPU-native stack lives or dies by keeping the traced path pure.

Traced scopes (where the rules apply):

- functions decorated with / passed by name into a JAX tracing
  combinator (``jax.jit``, ``vmap``, ``pmap``, ``grad``, ``lax.scan``,
  ``lax.cond``, ``while_loop``, ``fori_loop``, ``switch``, ``remat``,
  ``checkpoint``, ``eval_shape``, ``vjp``, ``pallas_call``, ...),
  including lambdas inline in those calls;
- functions registered as operators via ``@register_op`` — the op
  registry IS the jit path (CachedOp jits the whole dispatch walk);
- any function nested inside a traced scope.

Taint model: positional parameters without defaults are array inputs
(the invoke_op convention — arrays positional, statics keyword); names
assigned from tainted expressions become tainted.  Rules:

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
L001        ERROR     .item()/.asnumpy()/.tolist() on a tainted value in a
                      traced scope (host sync / concretization)
L002        ERROR     numpy host conversion (np.asarray/np.array/
                      onp.asarray/...) of a tainted value in a traced scope
L003        ERROR     float()/int()/bool() of a tainted value in a traced
                      scope (concretizes the tracer)
L004        WARNING   Python if/while branches on a tainted value (use
                      lax.cond/where; raises under jit, retraces at best)
L005        WARNING   sync point inside an ``engine.bulk`` region: a call
                      that forces the pending segment (.asnumpy()/.item()/
                      float()/print()/wait_all()...) splits the fused
                      program — the ops after it start a new segment
L006        WARNING   ``time.sleep`` or raw ``signal.signal`` outside
                      ``mxtpu/resilience/`` and ``preemption.py`` — ad-hoc
                      sleeps defeat the injectable-clock test discipline
                      (use RetryPolicy / a fault plan's delay action) and
                      raw signal handlers leak past exceptions (use
                      ``preemption.install``, which restores dispositions)
L007        INFO      dead ``# trace-ok`` suppression: the comment is
                      present but no diagnostic was suppressed on that
                      line — the hazard it excused is gone; delete the
                      comment so stale suppressions don't accumulate
L008        WARNING   direct mutation of BlockPool internals (an
                      assignment / augmented assignment / delete
                      targeting a ``._refs`` / ``._pins`` / ``._free``
                      attribute) outside ``mxtpu/parallel/paging.py`` —
                      bypasses the refcount invariants AND the
                      lifecycle sanitizer's shadow accounting
                      (``analysis/lifecycle_check.py``); go through
                      alloc/retain/pin/unpin/release
==========  ========  =====================================================

The L005 rule lints ``with ... bulk(...):`` bodies rather than traced
scopes: the bulk region is an explicit request to fuse, so every mid-
region flush is a fusion-breaker worth surfacing (docs/engine.md has the
sync-point matrix).  It reports at WARNING severity — the default
``--fail-on error`` CI gate ignores it; opt in with ``--fail-on
warning``.

False-positive escape hatch: append ``# trace-ok`` (optionally
``# trace-ok: reason``) to the flagged line.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Set, Union

from .diagnostics import Diagnostic, Report, Severity, register_pass

__all__ = ["trace_lint", "lint_source"]

_PASS = "trace_lint"

# call names (last dotted component) that trace their function arguments
_TRACING_COMBINATORS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "jacfwd",
    "jacrev", "hessian", "scan", "cond", "while_loop", "fori_loop",
    "switch", "associative_scan", "checkpoint", "remat", "eval_shape",
    "vjp", "jvp", "linearize", "custom_vjp", "custom_jvp", "shard_map",
    "pallas_call", "named_call", "xmap", "make_jaxpr",
}

# decorator names that mark a function as an op impl (jit path)
_OP_DECORATORS = {"register_op"}

_HOST_SYNC_METHODS = {"item", "asnumpy", "tolist"}
_NUMPY_MODULES = {"np", "onp", "numpy"}
_NUMPY_HOST_FNS = {"asarray", "array", "ascontiguousarray", "copy",
                   "asanyarray"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}
# attribute/call forms on a tainted name that are trace-safe (static
# metadata, not values)
_SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
               "weak_type"}


def _trace_ok_suppressed(lines: List[str], node: ast.AST,
                         span_node: Optional[ast.AST] = None,
                         used: Optional[Set[int]] = None) -> bool:
    """Honor "# trace-ok" anywhere on the lines the flagged expression
    spans (multi-line calls / conditions included) — shared by every
    rule so the suppression convention stays consistent.  Lines whose
    comment actually suppressed a diagnostic are recorded into ``used``
    so L007 can report the DEAD ones afterwards."""
    span = span_node if span_node is not None else node
    start = span.lineno
    end = getattr(span, "end_lineno", None) or start
    hit = False
    for ln in range(start, min(end, len(lines)) + 1):
        if 0 < ln <= len(lines) and "# trace-ok" in lines[ln - 1]:
            if used is not None:
                used.add(ln)
            hit = True
    return hit


def _trace_ok_comment_lines(source: str) -> Set[int]:
    """Line numbers carrying a real ``# trace-ok`` COMMENT token —
    tokenized, so the phrase inside a string literal or docstring (this
    very module documents the convention) never counts."""
    import io
    import tokenize

    out: Set[int] = set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT and "trace-ok" in tok.string:
                out.add(tok.start[0])
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # partial token stream: keep what was collected
    return out


def _dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for Attribute chains, 'jit' for bare Names."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_component(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _TracedScopeFinder(ast.NodeVisitor):
    """Collects function/lambda AST nodes that run under a JAX trace."""

    def __init__(self):
        self.traced: Set[ast.AST] = set()
        self.traced_names: Set[str] = set()
        self._defs = {}  # name -> [FunctionDef nodes]

    def visit_FunctionDef(self, node):
        self._defs.setdefault(node.name, []).append(node)
        for dec in node.decorator_list:
            base = dec.func if isinstance(dec, ast.Call) else dec
            last = _last_component(base)
            if last in _TRACING_COMBINATORS or last in _OP_DECORATORS:
                self.traced.add(node)
            # functools.partial(jax.jit, ...) style decorators
            if isinstance(dec, ast.Call) and last == "partial":
                for a in dec.args:
                    if _last_component(a) in _TRACING_COMBINATORS:
                        self.traced.add(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        last = _last_component(node.func)
        if last in _TRACING_COMBINATORS:
            for arg in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                if isinstance(arg, ast.Lambda):
                    self.traced.add(arg)
                elif isinstance(arg, ast.Name):
                    self.traced_names.add(arg.id)
        self.generic_visit(node)

    def resolve(self):
        for name in self.traced_names:
            for d in self._defs.get(name, ()):
                self.traced.add(d)
        return self.traced


def _tainted_params(fn: Union[ast.FunctionDef, ast.Lambda]) -> Set[str]:
    """Array-input heuristic: positionals without defaults + *varargs.
    Params WITH defaults are static op params (invoke_op passes statics
    by keyword); `self`/`cls` are never arrays."""
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    n_defaults = len(a.defaults)
    if n_defaults:
        names = names[:-n_defaults]
    out = {n for n in names if n not in ("self", "cls")}
    if a.vararg is not None:
        out.add(a.vararg.arg)
    return out


class _Taint(ast.NodeVisitor):
    """Does this expression reference a tainted name as a *value*?

    Attribute reads of static metadata (x.shape, x.ndim, ...) and calls
    like len()/isinstance() do not propagate taint."""

    def __init__(self, tainted: Set[str]):
        self.tainted = tainted
        self.hit = False

    def visit_Name(self, node):
        if node.id in self.tainted:
            self.hit = True

    def visit_Attribute(self, node):
        if node.attr in _SAFE_ATTRS:
            return  # x.shape / x.ndim — static under trace
        self.generic_visit(node)

    def visit_Call(self, node):
        fname = _last_component(node.func)
        if fname in ("len", "isinstance", "hasattr", "getattr", "type",
                     "id"):
            return  # metadata-only calls
        self.generic_visit(node)


def _is_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    t = _Taint(tainted)
    t.visit(expr)
    return t.hit


class _ScopeLinter(ast.NodeVisitor):
    """Lints one traced function body with simple forward taint flow."""

    def __init__(self, fname: str, lines: List[str], report: Report,
                 tainted: Set[str], used: Optional[Set[int]] = None):
        self.fname = fname
        self.lines = lines
        self.report = report
        self.tainted = set(tainted)
        self.used = used

    # -- helpers ---------------------------------------------------------
    def _suppressed(self, node, span_node=None) -> bool:
        return _trace_ok_suppressed(self.lines, node, span_node,
                                    used=self.used)

    def _emit(self, node, code, severity, subject, message,
              span_node=None):
        if self._suppressed(node, span_node):
            return
        self.report.add(Diagnostic(
            _PASS, code, severity, subject, message,
            location="%s:%d" % (self.fname, node.lineno)))

    def _taint_target(self, target):
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                self.tainted.add(n.id)

    # -- taint propagation ------------------------------------------------
    def visit_Assign(self, node):
        self.generic_visit(node)
        if _is_tainted(node.value, self.tainted):
            for t in node.targets:
                self._taint_target(t)

    def visit_AnnAssign(self, node):
        self.generic_visit(node)
        if node.value is not None and _is_tainted(node.value,
                                                  self.tainted):
            self._taint_target(node.target)

    def visit_AugAssign(self, node):
        self.generic_visit(node)
        if _is_tainted(node.value, self.tainted):
            self._taint_target(node.target)

    def visit_For(self, node):
        if _is_tainted(node.iter, self.tainted):
            self._taint_target(node.target)
        self.generic_visit(node)

    # -- rules ------------------------------------------------------------
    def visit_Call(self, node):
        func = node.func
        # L001: tainted.item() / .asnumpy() / .tolist()
        if isinstance(func, ast.Attribute) and \
                func.attr in _HOST_SYNC_METHODS:
            if _is_tainted(func.value, self.tainted):
                self._emit(
                    node, "L001", Severity.ERROR, func.attr,
                    ".%s() on a traced value forces a host sync and "
                    "raises under jit; keep the value on device "
                    "(jnp ops / lax.cond)" % func.attr)
        # L002: np.asarray(tainted) etc
        if isinstance(func, ast.Attribute) and \
                func.attr in _NUMPY_HOST_FNS:
            root = _dotted_name(func.value)
            if root in _NUMPY_MODULES and node.args and \
                    _is_tainted(node.args[0], self.tainted):
                self._emit(
                    node, "L002", Severity.ERROR,
                    "%s.%s" % (root, func.attr),
                    "%s.%s() of a traced value fails under jit "
                    "(tracers are not numpy-convertible); use jnp "
                    "equivalents" % (root, func.attr))
        # L003: float(tainted) / int(...) / bool(...)
        if isinstance(func, ast.Name) and func.id in _CAST_BUILTINS:
            if node.args and _is_tainted(node.args[0], self.tainted):
                self._emit(
                    node, "L003", Severity.ERROR, func.id,
                    "%s() of a traced value concretizes the tracer and "
                    "raises under jit" % func.id)
        self.generic_visit(node)

    def _check_branch(self, node, kind):
        if _is_tainted(node.test, self.tainted):
            self._emit(
                node, "L004", Severity.WARNING, kind,
                "Python `%s` on a traced value raises under jit "
                "(TracerBoolConversionError) or forces a retrace per "
                "value; use lax.cond / lax.while_loop / jnp.where"
                % kind, span_node=node.test)

    def visit_If(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_branch(node, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_Assert(self, node):
        # assert on a traced value is the same hazard as `if`
        if _is_tainted(node.test, self.tainted):
            self._emit(
                node, "L004", Severity.WARNING, "assert",
                "`assert` on a traced value raises under jit; use "
                "checkify or move the check outside the traced scope")
        # no generic_visit: message expr is host-side anyway

    # nested defs: handled by the outer pass (nested scopes of a traced
    # fn are traced too and linted with inherited taint); skip re-walk
    def visit_FunctionDef(self, node):
        sub = _ScopeLinter(self.fname, self.lines, self.report,
                           self.tainted | _tainted_params(node),
                           used=self.used)
        for stmt in node.body:
            sub.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        sub = _ScopeLinter(self.fname, self.lines, self.report,
                           self.tainted | _tainted_params(node),
                           used=self.used)
        sub.visit(node.body)


# sync-point call forms flagged inside a bulk region (L005)
_BULK_SYNC_METHODS = {"asnumpy", "item", "asscalar", "tolist",
                      "wait_to_read", "wait_to_write"}
_BULK_SYNC_CALLS = {"wait_all", "waitall"}
_BULK_SYNC_BUILTINS = {"float", "int", "bool", "print"}


class _BulkRegionLinter(ast.NodeVisitor):
    """L005: flag explicit sync points written inside a ``with ...
    bulk(...):`` body — each one flushes (and splits) the fused segment
    the region asked for.  Heuristic trigger: any with-item whose context
    expression is a call to a function named ``bulk``."""

    def __init__(self, fname: str, lines: List[str], report: Report,
                 used: Optional[Set[int]] = None):
        self.fname = fname
        self.lines = lines
        self.report = report
        self.used = used
        self._depth = 0  # > 0 while inside a bulk region

    def _emit(self, node, subject, what):
        if _trace_ok_suppressed(self.lines, node, used=self.used):
            return
        self.report.add(Diagnostic(
            _PASS, "L005", Severity.WARNING, subject,
            "%s inside an engine.bulk region flushes the pending "
            "segment — the fused program splits here; move the sync "
            "point outside the region (or suppress with `# trace-ok`)"
            % what,
            location="%s:%d" % (self.fname, node.lineno)))

    def visit_With(self, node):
        is_bulk = any(
            isinstance(item.context_expr, ast.Call)
            and _last_component(item.context_expr.func) == "bulk"
            for item in node.items)
        if is_bulk:
            self._depth += 1
        self.generic_visit(node)
        if is_bulk:
            self._depth -= 1

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        if self._depth > 0:
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in _BULK_SYNC_METHODS:
                self._emit(node, func.attr, ".%s()" % func.attr)
            else:
                last = _last_component(func)
                if last in _BULK_SYNC_CALLS:
                    self._emit(node, last, "%s()" % last)
                elif isinstance(func, ast.Name) and \
                        func.id in _BULK_SYNC_BUILTINS and any(
                            not isinstance(a, ast.Constant)
                            for a in node.args):
                    self._emit(node, func.id, "%s()" % func.id)
        self.generic_visit(node)


def _resilience_exempt(filename: str) -> bool:
    """L006 exemption: the resilience package owns the real sleeps (the
    default RetryPolicy/plan sleep implementations) and preemption.py
    owns the managed signal.signal calls."""
    norm = filename.replace("\\", "/")
    parts = norm.split("/")
    return "resilience" in parts or parts[-1] == "preemption.py"


class _HostHazardLinter(ast.NodeVisitor):
    """L006: module-wide scan for ``time.sleep`` / raw ``signal.signal``
    calls.  Unlike L001-L005 this is not scoped to traced regions — a
    bare sleep anywhere in library code defeats the injectable-clock
    test discipline, and a raw signal.signal leaks the handler when an
    exception skips the restore path."""

    def __init__(self, fname: str, lines: List[str], report: Report,
                 used: Optional[Set[int]] = None):
        self.fname = fname
        self.lines = lines
        self.report = report
        self.used = used

    def _emit(self, node, subject, message):
        if _trace_ok_suppressed(self.lines, node, used=self.used):
            return
        self.report.add(Diagnostic(
            _PASS, "L006", Severity.WARNING, subject, message,
            location="%s:%d" % (self.fname, node.lineno)))

    def visit_Call(self, node):
        name = _dotted_name(node.func)
        if name == "time.sleep":
            self._emit(
                node, "time.sleep",
                "time.sleep outside mxtpu/resilience: blocking sleeps "
                "belong behind an injectable sleep (RetryPolicy(sleep=...) "
                "/ a fault plan's delay action) so tests stay fast and "
                "deterministic")
        elif name == "signal.signal":
            self._emit(
                node, "signal.signal",
                "raw signal.signal outside preemption.py: an exception "
                "between install and restore leaks the handler — use "
                "mxtpu.preemption.install/uninstall, which always "
                "restores the previous disposition")
        self.generic_visit(node)


# BlockPool internals owned by mxtpu/parallel/paging.py (L008)
_POOL_INTERNALS = {"_refs", "_pins", "_free"}


def _paging_exempt(filename: str) -> bool:
    """L008 exemption: paging.py itself owns the pool internals."""
    norm = filename.replace("\\", "/")
    return norm.split("/")[-1] == "paging.py"


class _PoolInternalsLinter(ast.NodeVisitor):
    """L008: module-wide scan for statements that mutate BlockPool
    internals directly — ``pool._refs[bid] = 2``, ``pool._pins = {}``,
    ``del pool._free[0]``, ``pool._refs[bid] += 1``.  Like L006 this is
    not scoped to traced regions: an out-of-band refcount write anywhere
    silently desynchronizes both the pool invariants and the lifecycle
    sanitizer's shadow accounting."""

    def __init__(self, fname: str, lines: List[str], report: Report,
                 used: Optional[Set[int]] = None):
        self.fname = fname
        self.lines = lines
        self.report = report
        self.used = used

    @staticmethod
    def _internal_attr(target) -> Optional[str]:
        """The ``_refs``/``_pins``/``_free`` attr a write target reaches
        (``x._refs``, ``x._refs[i]``, ``x._free[a:b]``), else None."""
        node = target
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) and \
                node.attr in _POOL_INTERNALS:
            return node.attr
        return None

    def _check(self, stmt, targets):
        for t in targets:
            attr = self._internal_attr(t)
            if attr is None:
                continue
            if _trace_ok_suppressed(self.lines, stmt, used=self.used):
                continue
            self.report.add(Diagnostic(
                _PASS, "L008", Severity.WARNING, attr,
                "direct mutation of BlockPool internals (.%s) outside "
                "mxtpu/parallel/paging.py bypasses the refcount "
                "invariants and the lifecycle sanitizer's shadow "
                "accounting — go through alloc/retain/pin/unpin/release "
                "(or suppress a deliberate red-team write with "
                "`# trace-ok`)" % attr,
                location="%s:%d" % (self.fname, stmt.lineno)))

    def visit_Assign(self, node):
        self._check(node, node.targets)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._check(node, [node.target])
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check(node, [node.target])
        self.generic_visit(node)

    def visit_Delete(self, node):
        self._check(node, node.targets)
        self.generic_visit(node)


def lint_source(source: str, filename: str = "<string>") -> Report:
    """Lint one Python source string; returns a Report."""
    report = Report()
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as exc:
        report.add(Diagnostic(
            _PASS, "L000", Severity.ERROR, filename,
            "cannot parse: %s" % exc,
            location="%s:%s" % (filename, exc.lineno or 0)))
        return report
    lines = source.splitlines()

    finder = _TracedScopeFinder()
    finder.visit(tree)
    traced = finder.resolve()

    # drop traced scopes nested inside another traced scope: the outer
    # scope's linter already walks them (with inherited taint); linting
    # them standalone too would report every hazard twice
    nested = set()
    for fn in traced:
        for sub in ast.walk(fn):
            if sub is not fn and sub in traced:
                nested.add(sub)
    traced -= nested

    used: Set[int] = set()
    for fn in traced:
        tainted = _tainted_params(fn)
        linter = _ScopeLinter(filename, lines, report, tainted, used=used)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            linter.visit(stmt)

    _BulkRegionLinter(filename, lines, report, used=used).visit(tree)
    if not _resilience_exempt(filename):
        _HostHazardLinter(filename, lines, report, used=used).visit(tree)
    if not _paging_exempt(filename):
        _PoolInternalsLinter(filename, lines, report,
                             used=used).visit(tree)

    # L007: suppressions present but never consulted by a firing rule —
    # the hazard they excused is gone, so the comment is stale
    for ln in sorted(_trace_ok_comment_lines(source) - used):
        report.add(Diagnostic(
            _PASS, "L007", Severity.INFO, "trace-ok",
            "dead `# trace-ok` suppression: no diagnostic is suppressed "
            "on this line — the hazard it excused is gone; remove the "
            "stale comment",
            location="%s:%d" % (filename, ln)))
    return report


# per-file result cache keyed on (abspath, mtime_ns, size): the repo
# self-lints several times per process (tier-1 self-lint, the CLI `all`
# self-application, diagnose) and an unchanged file's findings are
# deterministic — the second full-package lint becomes ~free
_FILE_CACHE: dict = {}


def trace_lint(paths: Union[str, Iterable[str], None] = None) -> Report:
    """Lint .py files under the given paths (default: the mxtpu package
    directory — the repo self-lint).  Unchanged files (same mtime+size)
    are served from a per-process cache."""
    if paths is None:
        paths = [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))]
    elif isinstance(paths, str):
        paths = [paths]

    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", "_build", ".git")]
            files.extend(os.path.join(root, n) for n in sorted(names)
                         if n.endswith(".py"))

    report = Report()
    for f in sorted(files):
        try:
            st = os.stat(f)
            key = (os.path.abspath(f), st.st_mtime_ns, st.st_size)
            cached = _FILE_CACHE.get(key)
            if cached is not None:
                report.diagnostics.extend(cached)
                continue
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as exc:
            report.add(Diagnostic(
                _PASS, "L000", Severity.WARNING, f,
                "unreadable: %s" % exc))
            continue
        file_report = lint_source(src, filename=f)
        _FILE_CACHE[key] = list(file_report.diagnostics)
        report.extend(file_report)
    return report


register_pass(_PASS)(trace_lint)
