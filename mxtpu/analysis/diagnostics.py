"""Diagnostics core for mxtpu.analysis: located findings + pass registry.

The reference ran NNVM graph passes (InferShape, InferType, PlanMemory)
that *failed loudly per node* inside C++; our JAX-level stack either
swallows defects (``infer_shape`` → ``(None, None, None)``) or surfaces
them as opaque GSPMD/XLA errors at compile time.  Every analysis pass in
this package instead emits :class:`Diagnostic` records — (code, severity,
subject, message, location) — collected into a :class:`Report` the caller
can filter, print, or fail a build on.

Severity contract (docs/analysis.md):

- ``ERROR``   — a definite defect; the graph/registry/rules will misbehave.
- ``WARNING`` — likely defect or strong heuristic hit; review required.
- ``INFO``    — advisory (e.g. estimated reshard points, unverifiable ops).

Self-lint ("passes clean") means **zero ERROR diagnostics**.
"""

from __future__ import annotations

import enum
import json
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Severity", "Diagnostic", "Report", "register_pass", "get_pass",
           "list_passes", "run_pass"]


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


class Diagnostic:
    """One located finding produced by an analysis pass.

    subject: the exact node/rule/op name the finding is about — the
    acceptance contract is that every seeded defect is reported with the
    name a user would grep for.
    """

    __slots__ = ("pass_name", "code", "severity", "subject", "message",
                 "location", "details")

    def __init__(self, pass_name: str, code: str, severity: Severity,
                 subject: str, message: str,
                 location: Optional[str] = None,
                 details: Optional[Dict[str, Any]] = None):
        self.pass_name = pass_name
        self.code = code
        self.severity = Severity(severity)
        self.subject = subject
        self.message = message
        self.location = location
        self.details = dict(details or {})

    def to_dict(self) -> Dict[str, Any]:
        d = {"pass": self.pass_name, "code": self.code,
             "severity": str(self.severity), "subject": self.subject,
             "message": self.message}
        if self.location:
            d["location"] = self.location
        if self.details:
            d["details"] = {k: repr(v) if not isinstance(
                v, (str, int, float, bool, list, dict, type(None))) else v
                for k, v in self.details.items()}
        return d

    def __str__(self):
        loc = f"{self.location}: " if self.location else ""
        return (f"{loc}{str(self.severity)} {self.code} [{self.subject}] "
                f"{self.message}")

    def __repr__(self):
        return f"<Diagnostic {self}>"


class Report:
    """Ordered collection of diagnostics from one or more passes."""

    def __init__(self, diagnostics: Optional[List[Diagnostic]] = None):
        self.diagnostics: List[Diagnostic] = list(diagnostics or [])

    # -- building --------------------------------------------------------
    def add(self, *args, **kwargs) -> Diagnostic:
        d = args[0] if len(args) == 1 and isinstance(args[0], Diagnostic) \
            else Diagnostic(*args, **kwargs)
        self.diagnostics.append(d)
        return d

    def extend(self, other: "Report") -> "Report":
        self.diagnostics.extend(other.diagnostics)
        return self

    # -- querying --------------------------------------------------------
    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def __bool__(self):
        # a Report is always truthy as a container; use .ok for pass/fail
        return True

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics
                if d.severity == Severity.WARNING]

    @property
    def infos(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.INFO]

    @property
    def ok(self) -> bool:
        """True when the pass found no ERROR-level defects."""
        return not self.errors

    def filter(self, code: Optional[str] = None,
               subject: Optional[str] = None,
               min_severity: Optional[Severity] = None,
               pass_name: Optional[str] = None) -> "Report":
        out = self.diagnostics
        if code is not None:
            out = [d for d in out if d.code == code]
        if subject is not None:
            out = [d for d in out if d.subject == subject]
        if min_severity is not None:
            out = [d for d in out if d.severity >= min_severity]
        if pass_name is not None:
            out = [d for d in out if d.pass_name == pass_name]
        return Report(list(out))

    def subjects(self) -> List[str]:
        return [d.subject for d in self.diagnostics]

    # -- rendering -------------------------------------------------------
    def summary(self) -> str:
        return ("%d error(s), %d warning(s), %d info"
                % (len(self.errors), len(self.warnings), len(self.infos)))

    def to_json(self) -> str:
        return json.dumps([d.to_dict() for d in self.diagnostics], indent=2)

    def __str__(self):
        if not self.diagnostics:
            return "clean (no diagnostics)"
        lines = [str(d) for d in sorted(
            self.diagnostics, key=lambda d: -int(d.severity))]
        lines.append(self.summary())
        return "\n".join(lines)

    def __repr__(self):
        return f"<Report {self.summary()}>"


# -- pass registry -------------------------------------------------------
# Parity: nnvm::ApplyPass(graph, "InferShape") looked passes up by name in
# a global registry; custom passes register the same way here.

_PASS_REGISTRY: Dict[str, Callable[..., Report]] = {}


def register_pass(name: Optional[str] = None):
    """Decorator registering a callable(...) -> Report as a named pass."""

    def wrap(fn: Callable[..., Report]) -> Callable[..., Report]:
        pname = name or fn.__name__
        if pname in _PASS_REGISTRY:
            raise ValueError(f"analysis pass {pname!r} registered twice")
        _PASS_REGISTRY[pname] = fn
        return fn

    return wrap


def get_pass(name: str) -> Callable[..., Report]:
    try:
        return _PASS_REGISTRY[name]
    except KeyError:
        import difflib
        close = difflib.get_close_matches(name, _PASS_REGISTRY, n=3)
        hint = f"; close matches: {', '.join(close)}" if close else ""
        raise KeyError(f"no analysis pass named {name!r}{hint}") from None


def list_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


def run_pass(name: str, *args, **kwargs) -> Report:
    return get_pass(name)(*args, **kwargs)
