"""kernel_check: static TPU tile-geometry, VMEM-budget, and grid-safety
analysis for Pallas kernels.

The serving/training stack's Pallas kernels (``mxtpu.ops.pallas``:
flash_attention, conv_bwd, paged_attention) compile against TPU lowering
constraints — lane-aligned last dims, dtype-dependent sublane tiling,
the ~16 MiB VMEM ceiling per grid step — that until this pass lived only
in docstrings, and whose violation surfaces as an opaque Mosaic lowering
error *on hardware*.  In the NNVM-pass framing the rest of this package
adopts (InferShape/PlanMemory fail loudly per node before execution),
this is the pre-compile pass for kernel *call geometry*: every kernel
module exposes a small :class:`KernelSpec` descriptor — grid, per-operand
block shapes + index maps, scratch shapes, dtypes, scalar-prefetch
operands, as a function of the workload geometry — and the pass verdicts
it entirely on the host, so CPU-only CI can assert TPU-readiness.

Diagnostics (pass name ``kernel_check``; K0xx, plus the M007 VMEM
pricing INFO from :func:`~.memory_estimate.kernel_vmem_estimate`):

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
K001        ERROR     last block dim splits an axis into chunks that are
                      not a multiple of the 128-lane tile (a block
                      covering the FULL axis is exempt — partial lane
                      tiles pad — unless the dim is a ``strict_dims``
                      tile parameter like head_dim)
K002        ERROR     second-to-last block dim not a multiple of the
                      dtype's sublane tile (8 fp32 / 16 bf16 / 32 int8 —
                      the "block_size ≥ 32 for int8" rule, enforced via
                      ``strict_dims``); size-1 and full-axis dims are
                      otherwise exempt (padded partial tiles)
K003        ERROR     per-grid-step VMEM estimate (double-buffered in/out
                      blocks + scratch) exceeds the budget (default
                      16 MiB)
K004        ERROR     an index_map can address past the backing array's
                      extent for some in-range grid index (block-table
                      contents are modeled via the spec's scalar-prefetch
                      values — the null-page-0 convention is part of the
                      model, not special-cased)
K005        WARNING   scalar-prefetch table operand not int32, or its
                      value range unvalidated against the page-pool
                      extent (no ``valid_range`` declared)
K006        WARNING   grid ordering revisits a written output block — the
                      output's index map varies in a grid axis that runs
                      INSIDE an axis the output is reduced over (reduced
                      axes must be the innermost suffix)
K007        INFO      geometry is interpret-mode-only: the spec was
                      declared ``interpret=True`` and carries violations
                      that are legal on CPU tests but illegal on TPU — a
                      CPU-green suite must not claim TPU-readiness
K008        INFO      the K004 index-map sweep SAMPLED an oversized grid
                      (small axes full, large axes at edges+midpoint) —
                      the clean verdict is partial, never silent
K009        ERROR     mesh-axis/cache_spec mismatch: the spec declares a
                      shard_map partitioning (``mesh_axis``) whose shard
                      count does not divide the global sharded-axis
                      extent — GSPMD would pad or gather around the
                      kernel instead of running it per-device
M007        INFO      per-grid-step VMEM pricing breakdown (always
                      emitted per spec; PER-SHARD when the spec carries
                      a ``mesh_axis``)
==========  ========  =====================================================

Severity contract: K001–K004 are definite Mosaic-lowering/correctness
defects (ERROR); on a spec declared ``interpret=True`` the
TPU-lowering-only rules (K001/K002/K003) downgrade into one K007 INFO —
out-of-extent indexing (K004) stays an ERROR everywhere, interpret mode
included.  "Passes clean" means zero ERROR, same as every other pass.

Self-application: :func:`default_kernel_specs` builds the three shipped
kernels' descriptors at their real TPU serving/training geometries (fp32
and int8, decode and W-wide verify) and ``check_kernels()`` with no
arguments verdicts them — the merge gate every ROADMAP-item-2 kernel
lands behind (``python -m mxtpu.analysis kernel``, tier-1
``tests/test_kernel_check.py``).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from .diagnostics import Diagnostic, Report, Severity, register_pass
from .memory_estimate import (LANE, format_bytes, kernel_vmem_estimate,
                              parse_bytes, sublane_tile)

__all__ = ["BlockOperand", "ScratchOperand", "ScalarPrefetch",
           "KernelSpec", "check_kernels", "default_kernel_specs"]

_PASS = "kernel_check"

#: default per-grid-step budget: the ~16 MiB VMEM per TensorCore
DEFAULT_VMEM_BUDGET = 16 * (1 << 20)


class BlockOperand:
    """One windowed in/out operand of a pallas_call: the BlockSpec's
    block shape and index map plus the backing array's shape/dtype.

    ``index_map`` mirrors the real BlockSpec's: called with the grid
    indices followed by the spec's scalar-prefetch VALUES (numpy arrays
    — the same positional convention as PrefetchScalarGridSpec), it
    returns per-dim BLOCK indices (element offset = index × block dim).
    The checker evaluates it vectorized over the whole grid, so maps
    written with jnp/np ``where`` and fancy indexing — the real kernel
    maps — evaluate in a handful of dispatches.
    """

    __slots__ = ("name", "kind", "block_shape", "array_shape", "dtype",
                 "index_map", "strict_dims")

    def __init__(self, name: str, kind: str, block_shape: Sequence[int],
                 array_shape: Sequence[int], dtype,
                 index_map: Optional[Callable] = None,
                 strict_dims: Sequence[int] = ()):
        if kind not in ("in", "out"):
            raise ValueError("BlockOperand kind must be 'in' or 'out', "
                             "got %r" % (kind,))
        if len(tuple(block_shape)) != len(tuple(array_shape)):
            # the geometry and extent rules both align block dims with
            # array dims positionally; a rank mismatch would make them
            # disagree (and fail open on the unchecked trailing axes)
            raise ValueError(
                "BlockOperand %r: block_shape %r (rank %d) must have "
                "the same rank as array_shape %r (rank %d)"
                % (name, tuple(block_shape), len(tuple(block_shape)),
                   tuple(array_shape), len(tuple(array_shape))))
        self.name = name
        self.kind = kind
        self.block_shape = tuple(int(d) for d in block_shape)
        self.array_shape = tuple(int(d) for d in array_shape)
        self.dtype = dtype
        self.index_map = index_map
        # negative dim indices whose extent is an engine-CHOSEN tile
        # parameter (head_dim, block_size, q_block): the full-axis
        # exemption never applies there — a sub-tile choice is a real
        # defect the caller can fix, not workload-determined padding
        self.strict_dims = tuple(int(d) for d in strict_dims)

    def __repr__(self):
        return ("<BlockOperand %s %s block=%r array=%r %s>"
                % (self.kind, self.name, self.block_shape,
                   self.array_shape, self.dtype))


class ScratchOperand:
    """One VMEM scratch allocation (pltpu.VMEM(shape, dtype))."""

    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name: str, shape: Sequence[int], dtype):
        self.name = name
        self.shape = tuple(int(d) for d in shape)
        self.dtype = dtype


class ScalarPrefetch:
    """One scalar-prefetch operand (SMEM), with representative VALUES —
    e.g. a model block table using the null-page-0 convention — and the
    extent its values must stay within (``valid_range=(lo, hi)``,
    half-open; None = undeclared, which K005 flags)."""

    __slots__ = ("name", "values", "valid_range")

    def __init__(self, name: str, values,
                 valid_range: Optional[Tuple[int, int]] = None):
        import numpy as np
        self.name = name
        self.values = np.asarray(values)
        self.valid_range = (tuple(int(v) for v in valid_range)
                            if valid_range is not None else None)


class KernelSpec:
    """Statically-checkable descriptor of ONE pallas_call: grid,
    windowed operands, VMEM scratch, scalar-prefetch operands, and
    whether the call is interpret-mode-only (CPU tests).

    ``mesh_axis`` describes a shard_map-partitioned call (the serving
    kernels under a tp-sharded cache): a
    ``(axis_name, shards, global_extent)`` triple — mesh axis name, its
    shard count, and the GLOBAL extent of the sharded operand axis the
    per-shard geometry was derived from (kv heads for the paged
    kernels).  The spec's grid/operands then describe ONE shard, so
    K003 prices the per-device VMEM; a shard count that does not divide
    the global extent is a K009 ERROR."""

    __slots__ = ("name", "grid", "operands", "scratch", "prefetch",
                 "interpret", "mesh_axis")

    def __init__(self, name: str, grid: Sequence[int],
                 operands: Sequence[BlockOperand],
                 scratch: Sequence[ScratchOperand] = (),
                 prefetch: Sequence[ScalarPrefetch] = (),
                 interpret: bool = False,
                 mesh_axis: Optional[Tuple] = None):
        self.name = name
        self.grid = tuple(int(g) for g in grid)
        self.operands = list(operands)
        self.scratch = list(scratch)
        self.prefetch = list(prefetch)
        self.interpret = bool(interpret)
        if mesh_axis is not None:
            axis, shards = mesh_axis[0], int(mesh_axis[1])
            extent = int(mesh_axis[2]) if len(mesh_axis) > 2 else None
            mesh_axis = (str(axis), shards, extent)
        self.mesh_axis = mesh_axis

    def __repr__(self):
        return ("<KernelSpec %s grid=%r %d operand(s) %d scratch "
                "%d prefetch%s%s>"
                % (self.name, self.grid, len(self.operands),
                   len(self.scratch), len(self.prefetch),
                   " interpret" if self.interpret else "",
                   " mesh_axis=%r" % (self.mesh_axis,)
                   if self.mesh_axis else ""))


# -- geometry rules (K001/K002) -------------------------------------------

def _geometry_violations(spec: KernelSpec) -> List[Tuple[str, str, str]]:
    """(code, operand name, message) for every tile-geometry violation.

    The lane/sublane rules flag tilings that split an axis into
    non-tile-aligned chunks — misaligned strided windows Mosaic cannot
    lower.  Two exemptions, neither applying to an operand's
    ``strict_dims``: a block dim equal to the FULL array extent (no
    tiling choice exists; the hardware pads a partial tile — the
    rep*W-lane query block, conv's H+2 rows), and a size-1
    second-to-last dim (a single-sublane window lowers as a broadcast
    row — the lse/scale-vector pattern).  ``strict_dims`` marks
    engine-CHOSEN tile parameters (head_dim, block_size, q_block): a
    sub-tile value there is the fixable defect this pass exists for —
    the ROADMAP "block_size >= 32 for int8" rule."""
    out = []
    for op in spec.operands:
        bs = op.block_shape
        ar = op.array_shape
        if not bs:
            continue
        strict = {d % len(bs) for d in op.strict_dims}
        last = bs[-1]
        strict_last = (len(bs) - 1) in strict
        full_last = len(ar) >= 1 and last == ar[-1] and not strict_last
        if last % LANE != 0 and not full_last:
            out.append((
                "K001", op.name,
                "operand %r block %r: last dim %d is not a multiple of "
                "the %d-lane tile%s"
                % (op.name, bs, last, LANE,
                   " (a chosen tile parameter — pick a lane-aligned "
                   "value)" if strict_last else
                   " and does not cover the full %d-wide axis"
                   % (ar[-1] if ar else -1))))
        if len(bs) >= 2:
            sub = sublane_tile(op.dtype)
            second = bs[-2]
            strict_second = (len(bs) - 2) in strict
            exempt = (not strict_second
                      and (second == 1
                           or (len(ar) >= 2 and second == ar[-2])))
            if second % sub != 0 and not exempt:
                out.append((
                    "K002", op.name,
                    "operand %r block %r (%s): second-to-last dim %d is "
                    "not a multiple of the %s sublane tile %d (8 fp32 / "
                    "16 bf16 / 32 int8)%s"
                    % (op.name, bs, op.dtype, second, op.dtype, sub,
                       " — a chosen tile parameter; raise it to the "
                       "sublane floor" if strict_second else
                       " and does not cover the full axis")))
    return out


# -- index-map evaluation (K004/K006) -------------------------------------

def _prefetch_values(spec: KernelSpec):
    return tuple(pf.values for pf in spec.prefetch)


def _as_index_arrays(result, ndim: int, npoints: int):
    """Normalize an index_map result (tuple of scalars / numpy / jnp
    values) to per-dim int64 numpy arrays of shape (npoints,)."""
    import numpy as np

    if not isinstance(result, (tuple, list)):
        result = (result,)
    if len(result) != ndim:
        raise ValueError("index_map returned %d indices for a rank-%d "
                         "block" % (len(result), ndim))
    out = []
    for r in result:
        arr = np.asarray(r).astype(np.int64)
        out.append(np.broadcast_to(arr, (npoints,)) if arr.ndim == 0
                   else arr.reshape(npoints))
    return out


def _grid_points(grid: Tuple[int, ...], max_points: int):
    """(coords, sampled): per-axis index arrays covering the full grid
    product, or — past ``max_points`` — a partial sweep that keeps
    small axes (<= 64: slot/head-style table axes) FULL and samples
    only large axes at their edges + midpoint.  ``sampled=True`` means
    the K004 verdict is partial; the caller surfaces that as a K008
    INFO so a clean report never silently claims a full sweep."""
    import numpy as np

    total = 1
    for g in grid:
        total *= max(int(g), 1)
    if total <= max_points:
        axes = [np.arange(max(int(g), 1)) for g in grid]
        sampled = False
    else:
        def edge_pick(g):
            return np.asarray(sorted(
                x for x in {0, 1, g // 2, g - 2, g - 1} if 0 <= x < g))

        axes = []
        for g in grid:
            g = max(int(g), 1)
            axes.append(np.arange(g) if g <= 64 else edge_pick(g))
        kept = 1
        for a in axes:
            kept *= len(a)
        if kept > max_points:
            # many small axes can still blow the cap multiplicatively —
            # the cap is a hard memory bound, so fall back to edge
            # sampling everywhere
            axes = [edge_pick(max(int(g), 1)) for g in grid]
        sampled = True
    mesh = np.meshgrid(*axes, indexing="ij") if axes else []
    coords = [m.reshape(-1) for m in mesh]
    return coords, sampled


def _check_index_extents(spec: KernelSpec, report: Report,
                         max_points: int) -> None:
    import numpy as np

    pf_vals = _prefetch_values(spec)
    coords, sampled = _grid_points(spec.grid, max_points)
    npoints = len(coords[0]) if coords else 1
    if sampled:
        total = 1
        for g in spec.grid:
            total *= max(int(g), 1)
        report.add(Diagnostic(
            _PASS, "K008", Severity.INFO, spec.name,
            "index-map sweep SAMPLED the grid (%d of %d points: small "
            "axes full, large axes at edges+midpoint) — the K004 "
            "verdict is partial; raise max_grid_points for a full "
            "sweep" % (npoints, total),
            details={"points_checked": npoints, "grid_points": total}))
    for op in spec.operands:
        if op.index_map is None:
            continue
        try:
            res = op.index_map(*coords, *pf_vals)
            idx = _as_index_arrays(res, len(op.block_shape), npoints)
        except Exception as exc:
            report.add(Diagnostic(
                _PASS, "K004", Severity.ERROR,
                "%s.%s" % (spec.name, op.name),
                "operand %r index_map failed to evaluate over the grid "
                "(%s: %s) — the map must be a pure function of the grid "
                "indices and scalar-prefetch values"
                % (op.name, type(exc).__name__, exc)))
            continue
        for d, (ix, bdim, ext) in enumerate(
                zip(idx, op.block_shape, op.array_shape)):
            bad = (ix < 0) | (ix * bdim >= ext)
            if not bool(bad.any()):
                continue
            flat = int(np.argmax(bad))
            point = tuple(int(c[flat]) for c in coords)
            report.add(Diagnostic(
                _PASS, "K004", Severity.ERROR,
                "%s.%s" % (spec.name, op.name),
                "operand %r dim %d: index_map addresses block %d "
                "(elements from %d) past the backing array extent %d "
                "at in-range grid index %r — %d of %d checked grid "
                "point(s) out of bounds%s"
                % (op.name, d, int(ix[flat]), int(ix[flat]) * bdim,
                   ext, point, int(bad.sum()), npoints,
                   " (grid sampled at axis extremes)" if sampled
                   else ""),
                details={"dim": d, "grid_index": list(point),
                         "block_index": int(ix[flat]),
                         "extent": int(ext)}))


def _output_grid_dependence(spec: KernelSpec, op: BlockOperand):
    """Grid axes the output's index map depends on, probed per axis at
    1 and size-1 against the origin (affine maps — the real kernels' —
    are exactly captured; anything fancier still lands on the safe
    WARNING side)."""
    import numpy as np

    pf_vals = _prefetch_values(spec)

    def at(point):
        res = op.index_map(*point, *pf_vals)
        if not isinstance(res, (tuple, list)):
            res = (res,)
        return tuple(int(np.asarray(r)) for r in res)

    origin = tuple(0 for _ in spec.grid)
    base = at(origin)
    dependent = set()
    for axis, size in enumerate(spec.grid):
        # probe only IN-GRID points: a size-1 axis has nothing to vary
        # (and a phantom index could fault a table-driven map)
        for probe in {p for p in (1, size - 1) if 0 < p < size}:
            point = list(origin)
            point[axis] = probe
            if at(tuple(point)) != base:
                dependent.add(axis)
                break
    return dependent


def _check_output_revisit(spec: KernelSpec, report: Report) -> None:
    for op in spec.operands:
        if op.kind != "out" or op.index_map is None:
            continue
        try:
            dependent = _output_grid_dependence(spec, op)
        except Exception:
            continue  # un-probeable map: extent check already reported
        reduced = [ax for ax, size in enumerate(spec.grid)
                   if size > 1 and ax not in dependent]
        inner_dep = [ax for ax in dependent
                     if any(r < ax for r in reduced)]
        if not inner_dep:
            continue
        r = min(ax for ax in reduced if ax < max(inner_dep))
        report.add(Diagnostic(
            _PASS, "K006", Severity.WARNING,
            "%s.%s" % (spec.name, op.name),
            "output %r is written per grid axis %d but revisited "
            "across the OUTER reduced axis %d: each block is flushed "
            "and re-fetched once per outer step (and a j==0-style init "
            "re-zeros it) — make the reduced axes the innermost grid "
            "suffix" % (op.name, max(inner_dep), r),
            details={"dependent_axes": sorted(dependent),
                     "reduced_axes": reduced}))


def _check_prefetch(spec: KernelSpec, report: Report) -> None:
    import numpy as np

    for pf in spec.prefetch:
        vals = np.asarray(pf.values)
        if vals.dtype != np.int32:
            report.add(Diagnostic(
                _PASS, "K005", Severity.WARNING,
                "%s.%s" % (spec.name, pf.name),
                "scalar-prefetch operand %r is %s, not int32 — SMEM "
                "table walks index with int32; other widths reconvert "
                "per step or fail to lower" % (pf.name, vals.dtype)))
        if pf.valid_range is None:
            report.add(Diagnostic(
                _PASS, "K005", Severity.WARNING,
                "%s.%s" % (spec.name, pf.name),
                "scalar-prefetch operand %r declares no valid_range — "
                "its values are unvalidated against the page-pool "
                "extent, so a corrupt table walks out of the pool "
                "silently" % (pf.name,)))
        elif vals.size:
            lo, hi = pf.valid_range
            bad = int(((vals < lo) | (vals >= hi)).sum())
            if bad:
                report.add(Diagnostic(
                    _PASS, "K005", Severity.WARNING,
                    "%s.%s" % (spec.name, pf.name),
                    "scalar-prefetch operand %r: %d value(s) outside "
                    "the declared valid range [%d, %d) (min %d, max %d)"
                    % (pf.name, bad, lo, hi, int(vals.min()),
                       int(vals.max()))))


# -- the registered pass --------------------------------------------------

def check_kernels(specs: Optional[Sequence[KernelSpec]] = None,
                  vmem_budget=DEFAULT_VMEM_BUDGET,
                  buffering: int = 2,
                  max_grid_points: int = 1 << 20) -> Report:
    """Statically validate Pallas kernel call geometry; returns a Report
    of K0xx (+ M007) diagnostics.

    specs: KernelSpec descriptors (default: the shipped kernels' real
    TPU serving/training geometries via :func:`default_kernel_specs` —
    the repo self-application).  vmem_budget: per-grid-step ceiling, int
    or ``"16MiB"``-style string.  buffering: in/out block residency
    multiplier (the Pallas pipeline double-buffers; see
    :func:`~.memory_estimate.kernel_vmem_estimate`).  max_grid_points:
    full-product index-map sweep cap, beyond which large grid axes are
    sampled at their extremes (small axes stay fully swept) and a K008
    INFO marks the verdict partial.
    """
    if specs is None:
        specs = default_kernel_specs()
    budget = parse_bytes(vmem_budget)
    report = Report()
    for spec in specs:
        deferred: List[Tuple[str, str, str]] = []

        # K009 — mesh-axis/cache_spec divisibility (ERROR everywhere:
        # a partitioning the mesh cannot honor is wrong in interpret
        # mode too — GSPMD would pad or gather around the kernel)
        if spec.mesh_axis is not None:
            axis, shards, extent = spec.mesh_axis
            if shards < 1 or (extent is not None
                              and extent % max(shards, 1) != 0):
                report.add(Diagnostic(
                    _PASS, "K009", Severity.ERROR, spec.name,
                    "mesh-axis mismatch: cache_spec shards axis %r "
                    "over %d device(s) but the global sharded-axis "
                    "extent %s does not divide — shard_map cannot "
                    "place whole kv heads per device; fix the mesh "
                    "size or the cache_spec heads axis"
                    % (axis, shards, extent),
                    details={"axis": axis, "shards": shards,
                             "global_extent": extent}))

        # K001/K002 — tile geometry
        for code, opname, msg in _geometry_violations(spec):
            if spec.interpret:
                deferred.append((code, opname, msg))
            else:
                report.add(Diagnostic(
                    _PASS, code, Severity.ERROR,
                    "%s.%s" % (spec.name, opname), msg))

        # K003 / M007 — VMEM budget + pricing
        est = kernel_vmem_estimate(spec, buffering=buffering)
        report.add(Diagnostic(
            _PASS, "M007", Severity.INFO, spec.name,
            "per-grid-step VMEM estimate: total=%s (%dx(in=%s + out=%s)"
            " + scratch=%s), smem prefetch=%s, budget=%s"
            % (format_bytes(est["total_bytes"]), est["buffering"],
               format_bytes(est["in_bytes"]),
               format_bytes(est["out_bytes"]),
               format_bytes(est["scratch_bytes"]),
               format_bytes(est["smem_prefetch_bytes"]),
               format_bytes(budget)),
            details={k: v for k, v in est.items() if k != "per_operand"}))
        if est["total_bytes"] > budget:
            msg = ("per-grid-step VMEM estimate %s exceeds the %s "
                   "budget by %s — shrink the block/scratch shapes or "
                   "stream the oversized operand (largest: %s)"
                   % (format_bytes(est["total_bytes"]),
                      format_bytes(budget),
                      format_bytes(est["total_bytes"] - budget),
                      ", ".join("%s=%s" % (n, format_bytes(b))
                                for n, _k, _s, _d, b in sorted(
                                    est["per_operand"],
                                    key=lambda t: -t[-1])[:3])))
            if spec.interpret:
                deferred.append(("K003", spec.name, msg))
            else:
                report.add(Diagnostic(_PASS, "K003", Severity.ERROR,
                                      spec.name, msg,
                                      details={"total_bytes":
                                               est["total_bytes"],
                                               "budget_bytes": budget}))

        # K004 — index maps stay inside their arrays (ERROR everywhere:
        # out-of-extent reads are wrong in interpret mode too)
        _check_index_extents(spec, report, max_grid_points)

        # K005 — scalar-prefetch hygiene
        _check_prefetch(spec, report)

        # K006 — output-revisit grid ordering
        _check_output_revisit(spec, report)

        # K007 — interpret-only downgrade summary
        if deferred:
            report.add(Diagnostic(
                _PASS, "K007", Severity.INFO, spec.name,
                "geometry is interpret-mode-only: %d TPU-lowering "
                "violation(s) [%s] are legal on CPU tests but would "
                "fail Mosaic on hardware — this suite being green does "
                "NOT claim TPU-readiness for %r"
                % (len(deferred),
                   ", ".join(sorted({c for c, _o, _m in deferred})),
                   spec.name),
                details={"violations": [
                    {"code": c, "operand": o, "message": m}
                    for c, o, m in deferred]}))
    return report


# -- repo self-application ------------------------------------------------

def default_kernel_specs() -> List[KernelSpec]:
    """The shipped kernels' descriptors at their REAL TPU geometries —
    the set ``check_kernels()`` (and ``python -m mxtpu.analysis
    kernel``) verdicts as the merge gate:

    - flash_attention fwd + both backward kernels, fp32 training shape
      and the bf16 serving-prefill shape (T=2048, D=128, 128/128
      blocks);
    - conv_bwd at the ResNet small-channel stage its VMEM gate admits
      (56x56x64, fp32);
    - paged_attention decode (W=1) and W-wide speculative verify (W=8),
      fp32 cache at block_size 16 and int8 cache at block_size 32 (the
      int8 sublane floor), GQA rep 4, D=128, ragged model tables — plus
      the shard_map-partitioned (``mesh_axis=("tp", 4)``) per-shard
      variants of the decode and int8-verify geometries, the default
      fast path under a tp-sharded cache;
    - paged_attention TREE verify (``tree=True``: ancestor-bitmask
      lane masking over a model binary tree) at W=4 and W=8, fp32 and
      int8 caches, plus the tp=2 per-shard int8 tree geometry — the
      serving engines' spec_tree fast path;
    - paged_prefill chunked-prefill at the serving chunk (T=128, GQA
      rep 4, D=128), fp32 cache at block_size 16 and int8 at 32, plus
      the tp=4 per-shard variant.
    """
    import importlib

    from ..ops.pallas import conv_bwd, paged_attention

    # the package re-exports the flash_attention FUNCTION under the
    # module's name; import the module itself for its spec builder
    flash_attention = importlib.import_module(
        "mxtpu.ops.pallas.flash_attention")
    prefill_attention = importlib.import_module(
        "mxtpu.ops.pallas.prefill_attention")

    specs: List[KernelSpec] = []
    for dtype in ("float32", "bfloat16"):
        specs.extend(flash_attention.kernel_specs(
            B=4, H=8, T=2048, D=128, dtype=dtype))
    specs.append(conv_bwd.kernel_spec(N=8, H=56, W=56, Ci=64, Co=64,
                                      dtype="float32"))
    for cache_dtype, block_size in (("float32", 16), ("int8", 32)):
        for W in (1, 8):
            specs.append(paged_attention.kernel_spec(
                B=16, KV=8, rep=4, W=W, D=128, block_size=block_size,
                max_length=512, cache_dtype=cache_dtype))
    # the GSPMD-partitioned serving path: per-shard (tp=4 over 8 global
    # kv heads -> 2 per device) decode and int8-verify geometries
    specs.append(paged_attention.kernel_spec(
        B=16, KV=8, rep=4, W=1, D=128, block_size=16, max_length=512,
        cache_dtype="float32", mesh_axis=("tp", 4)))
    specs.append(paged_attention.kernel_spec(
        B=16, KV=8, rep=4, W=8, D=128, block_size=32, max_length=512,
        cache_dtype="int8", mesh_axis=("tp", 4)))
    # tree-speculative verify: per-lane ancestor bitmasks over a model
    # binary tree (the engines' spec_tree path), fp32 + int8, and the
    # tp-sharded int8 variant
    for cache_dtype, block_size in (("float32", 16), ("int8", 32)):
        for W in (4, 8):
            specs.append(paged_attention.kernel_spec(
                B=16, KV=8, rep=4, W=W, D=128, block_size=block_size,
                max_length=512, cache_dtype=cache_dtype, tree=True))
    specs.append(paged_attention.kernel_spec(
        B=16, KV=8, rep=4, W=8, D=128, block_size=32, max_length=512,
        cache_dtype="int8", tree=True, mesh_axis=("tp", 2)))
    # chunked-prefill kernel at the serving chunk geometry
    for cache_dtype, block_size in (("float32", 16), ("int8", 32)):
        specs.append(prefill_attention.kernel_spec(
            T=128, KV=8, rep=4, D=128, block_size=block_size,
            max_length=2048, start_pos=512, cache_dtype=cache_dtype))
    specs.append(prefill_attention.kernel_spec(
        T=128, KV=8, rep=4, D=128, block_size=16, max_length=2048,
        start_pos=512, cache_dtype="float32", mesh_axis=("tp", 4)))
    return specs


register_pass(_PASS)(check_kernels)
