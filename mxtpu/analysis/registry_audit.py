"""audit_registry: metadata consistency checks over _OP_REGISTRY.

The registry is the single source of truth for both `mx.nd.*` and
`mx.sym.*`; wrong metadata corrupts *graphs*, not just calls: a wrong
``num_outputs`` makes tuple-unpacking of a symbol silently mis-wire, and
``differentiable=True`` on a vjp-rejecting op turns `backward()` into a
deep JAX traceback.  This pass abstractly evaluates every op it can
(jax.eval_shape on sample shapes — no FLOPs, CPU-safe) and cross-checks:

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
R001        ERROR     alias table broken: a name maps to a spec whose
                      canonical name maps to a DIFFERENT spec object
R002        ERROR     declared num_outputs contradicts abstract eval
R003        ERROR     differentiable=True but jax.vjp rejects the op
R004        INFO      op could not be abstractly evaluated on any sample
                      shape (requires structured/static args) — unverified
R005        WARNING   a declared fault-injection site
                      (resilience.faults.SITES) is never named by any
                      fault plan in the test suite — its wiring has lost
                      deterministic coverage (:func:`audit_fault_sites`)
==========  ========  =====================================================

The R005 cross-check (``audit_fault_sites``) scans the STRING LITERALS
of the tests/ tree for PLAN-shaped mentions of each declared site: the
site name followed by a ``:raise``/``:delay`` action in the same
literal (split literals — f-strings, adjacent strings, and ``"a" +
"b"`` concatenation chains — are rejoined before matching, so a plan a
formatter wrapped across fragments keeps its coverage credit).  Bare mentions (comments, docstrings, assertion messages —
and this audit's own fixtures) never count, and the injector-level
fault matrix (tests/test_resilience.py) is parametrized over ``SITES``
with ``"%s@..."`` literals and so proves only the injector; what R005
protects is the *wiring-level* plans —
``fault_plan("serving.swap_in@1:raise=...")`` style tests that drive
the real subsystem through the site — so sites like
``serving.swap_out/in`` can't silently lose their coverage as suites
are trimmed.

Sample-shape protocol: positional parameters without defaults are array
inputs (the invoke_op convention: arrays positional, statics keyword);
each op is tried on 2-D, then 3-D, then 4-D, then 1-D float32 samples
until one abstract-evals.  Ops needing required keyword-only args,
integer inputs, or runtime-injected state (rng key) land in R004.

Cost model (the tier-1 budget): abstract evals dominate.  Two measures
keep the full-registry run cheap enough for tier-1 (was ~17s):

- R002 and R003 share the proven abstract inputs: the plain eval finds
  a working shape candidate first, then differentiable ops pay exactly
  ONE extra vjp-probe eval on those structs (probing vjp across all
  candidates measured slower — vjp traces cost ~2x);
- results are cached per op (keyed on the spec's fn identity — held
  strongly so a re-registered op can never collide — plus arity and the
  differentiable flag), making every repeat audit in a process — the
  test suite runs several — near-free.
"""

from __future__ import annotations

import inspect
from typing import Dict, Iterable, Optional

from ..base import _OP_REGISTRY
from .diagnostics import Diagnostic, Report, Severity, register_pass

__all__ = ["audit_registry", "audit_fault_sites"]

_PASS = "audit_registry"

# candidate sample shapes, tried in order until abstract eval succeeds
_SHAPE_CANDIDATES = ((2, 4), (2, 3, 4), (2, 3, 4, 4), (4,))

# op name -> (fn, n_req, differentiable, (structs, outs, err, vjp_exc));
# fn is the cache validity token (identity-compared against the live
# spec) and the differentiable flag must match too — flipping it on
# re-registration changes the R003 verdict for the same fn
_EVAL_CACHE: Dict[str, tuple] = {}


def _required_arity(fn):
    """(n_required_positional, has_varargs, has_required_kwonly)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return None
    n = 0
    varargs = False
    kwonly_required = False
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            if p.default is p.empty:
                n += 1
        elif p.kind == p.VAR_POSITIONAL:
            varargs = True
        elif p.kind == p.KEYWORD_ONLY and p.default is p.empty:
            kwonly_required = True
    return n, varargs, kwonly_required


_SIGNATURE_ERROR_HINTS = ("required positional", "unexpected keyword",
                          "missing", "takes", "required argument")


def _try_abstract_eval(fn, arity):
    """First successful (structs, out) over the shape candidates, else
    (None, last_error).  Signature-level TypeErrors bail immediately —
    a different input rank cannot supply a missing static kwarg, and the
    retries are the dominant cost of auditing a 300-op registry."""
    import jax
    import jax.numpy as jnp

    last = None
    last_msg = None
    for shape in _SHAPE_CANDIDATES:
        structs = tuple(jax.ShapeDtypeStruct(shape, jnp.float32)
                        for _ in range(arity))
        try:
            out = jax.eval_shape(lambda *a: fn(*a), *structs)
            return structs, out
        except TypeError as exc:
            msg = str(exc)
            if any(h in msg for h in _SIGNATURE_ERROR_HINTS):
                return None, exc
            if last_msg is not None and msg == last_msg:
                return None, exc  # shape-independent failure
            last, last_msg = exc, msg
        except Exception as exc:
            msg = str(exc)
            if last_msg is not None and msg == last_msg:
                return None, exc  # same error on a different rank
            last, last_msg = exc, msg
    return None, last


def _make_vjp_probe(fn):
    """Fused R002+R003 probe: jax.vjp through the op AND the primal
    outputs from one abstract eval (the cotangents are ones of the
    output avals, built inside the trace)."""
    import jax
    import jax.numpy as jnp

    def _probe(*arrs):
        res, vjp_fn = jax.vjp(lambda *a: fn(*a), *arrs)
        if isinstance(res, tuple):
            cts = tuple(jnp.ones(o.shape, o.dtype) for o in res)
        else:
            cts = jnp.ones(res.shape, res.dtype)
        vjp_fn(cts)
        return res

    return _probe


def _probe_op(spec, n_req):
    """Cached abstract probe of one op: returns ``(structs, outs, err,
    vjp_exc)``.  ``structs is None`` means not abstractly evaluable
    (``err`` holds the last exception); ``vjp_exc`` is the captured
    jax.vjp rejection of a differentiable op whose plain eval succeeded
    (the R003 evidence)."""
    import jax
    import jax.numpy as jnp

    cached = _EVAL_CACHE.get(spec.name)
    if cached is not None and cached[0] is spec.fn \
            and cached[1] == n_req \
            and cached[2] == bool(spec.differentiable):
        return cached[3]

    structs, out = _try_abstract_eval(spec.fn, n_req)
    if structs is None:
        result = (None, None, out, None)
    else:
        outs = out if isinstance(out, tuple) else (out,)
        vjp_exc = None
        if spec.differentiable and all(
                jnp.issubdtype(o.dtype, jnp.inexact) for o in outs):
            # one vjp probe on the structs the plain eval proved work
            # (the abstract inputs are shared between the two rules);
            # retrying vjp across shape candidates measured SLOWER than
            # this plain-first order — vjp traces cost ~2x
            try:
                jax.eval_shape(_make_vjp_probe(spec.fn), *structs)
            except Exception as exc:
                vjp_exc = exc
        result = (structs, outs, None, vjp_exc)
    _EVAL_CACHE[spec.name] = (spec.fn, n_req, bool(spec.differentiable),
                              result)
    return result


# -- R005: fault-site coverage --------------------------------------------

# (paths tuple) -> frozenset of string literals; test sources don't
# change within a process, and the audit runs several times per suite
_LITERAL_CACHE: Dict[tuple, frozenset] = {}


def _default_test_dir() -> Optional[str]:
    """The repo's tests/ tree: a sibling of the installed mxtpu package
    (present in the development checkout, absent in a wheel install)."""
    import os

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cand = os.path.join(os.path.dirname(pkg_dir), "tests")
    return cand if os.path.isdir(cand) else None


def _literal_fragments(node):
    """Constant-string fragments of a literal, an f-string, or a
    ``"a" + "b"`` concatenation chain, in source order.  Non-literal
    pieces (formatted values, names) contribute nothing — the same hole
    an f-string leaves.  (Adjacent string literals, ``"a" "b"``, are
    already merged into one Constant by the parser.)"""
    import ast

    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value
    elif isinstance(node, ast.JoinedStr):
        for v in node.values:
            yield from _literal_fragments(v)
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        yield from _literal_fragments(node.left)
        yield from _literal_fragments(node.right)


def _string_literals(paths) -> frozenset:
    """Every str constant in the given python files/dirs (AST-level, so
    comments never count as coverage).  Split plan literals — f-strings
    (``f"site@{i}:raise"``), parenthesized adjacent strings, and
    ``"site" + "@1:raise"`` BinOp concatenations — are rejoined first so
    a plan token that the source splits across fragments still lands in
    ONE scanned literal (a split plan is real coverage; losing it to
    formatting was the R005 false-positive this guards against)."""
    import ast
    import os

    key = tuple(paths)
    cached = _LITERAL_CACHE.get(key)
    if cached is not None:
        return cached
    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs.sort()
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    lits = set()
    for f in files:
        try:
            with open(f, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=f)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, (ast.Constant, ast.JoinedStr)) or (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Add)):
                joined = "".join(_literal_fragments(node))
                if joined:
                    lits.add(joined)
    result = frozenset(lits)
    _LITERAL_CACHE[key] = result
    return result


def _plan_rule_re(site: str):
    """Regex matching ``site`` used as a PLAN RULE inside a literal:
    the site immediately followed by plan syntax (``#key`` / ``@n`` /
    ``+`` / ``xN`` / ``%N`` / ``:``, no intervening whitespace or
    quote) reaching a ``:raise``/``:delay`` action — one TOKEN, so a
    site list or prose sharing a literal with another site's plan
    earns no cross-credit."""
    import re

    return re.compile(re.escape(site)
                      + r"(?=[#@%x+:])[^\s'\"]*:(?:raise|delay)")


def audit_fault_sites(test_paths: Optional[Iterable[str]] = None,
                      sites: Optional[Iterable[str]] = None) -> Report:
    """Cross-check ``resilience.faults.SITES`` against the fault plans
    the test suite actually writes: one R005 WARNING per declared site
    that no test injects via a PLAN-shaped string literal (the site
    followed by a ``:raise``/``:delay`` action in the same literal —
    the ``fault_plan("serving.swap_in#%d@1:raise=...")`` form).

    test_paths: files/dirs to scan (default: the repo tests/ tree; when
    none is found — wheel installs — the audit is a silent no-op).
    sites: override the site list (tests use this for red-team
    fixtures)."""
    report = Report()
    if sites is None:
        from ..resilience.faults import SITES as sites
    if test_paths is None:
        d = _default_test_dir()
        if d is None:
            return report
        test_paths = [d]
    lits = _string_literals(list(test_paths))
    for site in sites:
        rx = _plan_rule_re(site)
        if any(rx.search(lit) for lit in lits):
            continue
        report.add(Diagnostic(
            _PASS, "R005", Severity.WARNING, site,
            "declared fault site %r is never named by any fault plan "
            "in the scanned tests — its failure-path wiring has lost "
            "deterministic coverage; add a fault_plan(%r...) test or "
            "retire the site from resilience.faults.SITES"
            % (site, site + "@1:raise")))
    return report


def audit_registry(ops: Optional[Iterable[str]] = None,
                   include_unverified: bool = False,
                   fault_sites: bool = True) -> Report:
    """Audit registered operators; returns a Report.

    ops: optional subset of registry names to audit (default: every
    unique spec).  include_unverified: emit an R004 INFO per op that
    could not be abstractly evaluated (off by default — roughly a third
    of the registry takes structured args).  fault_sites: also run the
    R005 fault-site coverage cross-check over the repo tests/ tree
    (:func:`audit_fault_sites`; a no-op when no tests dir exists).
    """
    import jax
    import jax.numpy as jnp

    report = Report()

    if ops is None:
        names = list(_OP_REGISTRY)
    else:
        names = [n for n in ops]

    # -- R001: alias table has exactly one spec object per op ------------
    seen_specs = {}
    for name in names:
        spec = _OP_REGISTRY.get(name)
        if spec is None:
            report.add(Diagnostic(
                _PASS, "R001", Severity.ERROR, name,
                "requested op %r is not in the registry" % name))
            continue
        canonical = _OP_REGISTRY.get(spec.name)
        if canonical is not spec:
            report.add(Diagnostic(
                _PASS, "R001", Severity.ERROR, name,
                "alias table broken: %r maps to a spec whose canonical "
                "name %r maps to a different spec object" %
                (name, spec.name)))
        seen_specs.setdefault(id(spec), spec)

    specs = list(seen_specs.values())

    for spec in sorted(specs, key=lambda s: s.name):
        arity = _required_arity(spec.fn)
        if arity is None:
            continue
        n_req, varargs, kwonly_required = arity
        if kwonly_required or (varargs and n_req == 0) or n_req == 0:
            if include_unverified:
                report.add(Diagnostic(
                    _PASS, "R004", Severity.INFO, spec.name,
                    "op %r not abstractly verified (required keyword "
                    "args / varargs-only / nullary)" % spec.name))
            continue

        structs, outs, err, vjp_exc = _probe_op(spec, n_req)
        if structs is None:
            if include_unverified:
                report.add(Diagnostic(
                    _PASS, "R004", Severity.INFO, spec.name,
                    "op %r not abstractly verified on sample shapes "
                    "(%s)" % (spec.name, repr(err)[:120])))
            continue

        # -- R002: declared num_outputs vs abstract reality --------------
        declared = spec.num_outputs
        if callable(declared):
            try:
                declared = declared({})
            except Exception:
                declared = None  # arity genuinely depends on kwargs
        if declared is not None and declared != len(outs):
            report.add(Diagnostic(
                _PASS, "R002", Severity.ERROR, spec.name,
                "op %r declares num_outputs=%d but abstract eval on "
                "shape %s produced %d output(s); symbolic tuple "
                "unpacking will mis-wire" %
                (spec.name, declared, structs[0].shape, len(outs)),
                details={"declared": declared, "observed": len(outs)}))
        elif spec.num_outputs is None and len(outs) > 1:
            # the engine bulker (and symbolic unpacking) treat an
            # undeclared arity as "exactly one output"; a silent
            # multi-output op would hand callers a single lazy handle
            # for a tuple result
            report.add(Diagnostic(
                _PASS, "R002", Severity.ERROR, spec.name,
                "op %r returns %d outputs but declares no num_outputs; "
                "engine.bulk assumes undeclared ops are single-output — "
                "declare num_outputs=%d in register_op" %
                (spec.name, len(outs), len(outs)),
                details={"declared": None, "observed": len(outs)}))

        # -- R003: differentiable ops must admit jax.vjp -----------------
        # only flagged when every output is inexact (a float cotangent
        # exists); integer outputs on a differentiable op are legal for
        # shape-dependent index outputs.  The probe already ran (fused
        # with the R002 eval); vjp_exc is the captured rejection.
        if vjp_exc is not None:
            report.add(Diagnostic(
                _PASS, "R003", Severity.ERROR, spec.name,
                "op %r is registered differentiable=True but "
                "jax.vjp rejects it (%s); autograd recording would "
                "fail — register with differentiable=False" %
                (spec.name, repr(vjp_exc)[:200]),
                details={"error": repr(vjp_exc)}))

    if fault_sites and ops is None:
        # full-registry audits carry the suite-level cross-check; a
        # subset audit (ops=[...]) is about those ops only
        report.extend(audit_fault_sites())

    return report


register_pass(_PASS)(audit_registry)
