"""check_sharding: static validation of ShardingRules against params+mesh.

A bad PartitionSpec today surfaces as an opaque GSPMD error deep inside
XLA compilation ("sharding annotation ... dimension 0 is not divisible");
this pass evaluates the rule list against the actual parameter shapes and
mesh *before* any device_put or jit, and names the exact rule/param:

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
S001        ERROR     spec has more axes than the matched param has dims
S002        ERROR     spec names a mesh axis the mesh does not have
S003        ERROR     mesh-axis size does not divide the param dimension
S004        ERROR     one mesh axis used on two dimensions of one spec
S005        WARNING   dead rule: its pattern matches no param
S006        WARNING   shadowed rule: matches params but never wins
                      (an earlier rule always matches first)
S007        INFO      estimated reshard point: params in one layer group
                      place the same mesh axis on different dims
==========  ========  =====================================================

S007 is a heuristic: Megatron column→row pairs (q_proj ('tp', None) then
out_proj (None, 'tp')) intentionally alternate and compile to a single
all-reduce — treat the INFO as "look here", not "defect".
"""

from __future__ import annotations

import re
from typing import Dict, Union

from .diagnostics import Diagnostic, Report, Severity, register_pass

__all__ = ["check_sharding"]

_PASS = "check_sharding"


def _mesh_axis_sizes(mesh) -> Dict[str, int]:
    """Accepts a DeviceMesh, a jax Mesh, or a plain {axis: size} dict
    (handy for CPU-only tests with no real device mesh)."""
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    jm = getattr(mesh, "jax_mesh", mesh)
    return {str(k): int(v) for k, v in dict(jm.shape).items()}


def _spec_entries(spec):
    """Flatten one PartitionSpec into (dim, axis_name) pairs; a tuple
    entry shards one dim over several mesh axes."""
    out = []
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        for name in names:
            out.append((dim, str(name)))
    return out


def check_sharding(rules, params: Dict[str, Union[tuple, object]],
                   mesh) -> Report:
    """Validate `rules` (a ShardingRules) against named params and a mesh.

    params: name → array-like (anything with .shape) or a bare shape
    tuple.  mesh: DeviceMesh / jax Mesh / {axis: size} dict.
    """
    report = Report()
    axis_sizes = _mesh_axis_sizes(mesh)
    rule_list = rules.iter_rules()

    shapes = {}
    for name, p in params.items():
        shapes[name] = tuple(getattr(p, "shape", p))

    # per-rule match bookkeeping for dead/shadowed detection: one scan
    # per (rule, param); the winner is the first matching index (same
    # first-match contract as ShardingRules.spec_for)
    compiled = [re.compile(pat) for pat, _ in rule_list]
    matches = [[] for _ in rule_list]   # names the pattern matches at all
    wins = [[] for _ in rule_list]      # names where the rule is first
    winner_of = {}                      # name -> rule index (or None)
    for name in shapes:
        first = None
        for i, pat in enumerate(compiled):
            if pat.search(name):
                matches[i].append(name)
                if first is None:
                    first = i
        winner_of[name] = first
        if first is not None:
            wins[first].append(name)

    # -- per-param spec validation ---------------------------------------
    for name in sorted(shapes):
        idx = winner_of[name]
        if idx is None:
            continue  # replicate default — always valid
        pattern, spec = rule_list[idx]
        shape = shapes[name]
        subject = name
        if len(spec) > len(shape):
            report.add(Diagnostic(
                _PASS, "S001", Severity.ERROR, subject,
                "rule %r spec %s has %d axes but param %r has only "
                "%d dims %s" % (pattern, spec, len(spec), name,
                                len(shape), shape),
                details={"rule": pattern}))
            continue
        used = {}
        for dim, axis in _spec_entries(spec):
            if axis not in axis_sizes:
                report.add(Diagnostic(
                    _PASS, "S002", Severity.ERROR, subject,
                    "rule %r spec %s names mesh axis %r which the mesh "
                    "does not define (axes: %s)" %
                    (pattern, spec, axis, sorted(axis_sizes)),
                    details={"rule": pattern, "axis": axis}))
                continue
            if axis in used:
                report.add(Diagnostic(
                    _PASS, "S004", Severity.ERROR, subject,
                    "rule %r spec %s uses mesh axis %r on dims %d and "
                    "%d of param %r; a mesh axis may shard at most one "
                    "dim" % (pattern, spec, axis, used[axis], dim, name),
                    details={"rule": pattern, "axis": axis}))
                continue
            used[axis] = dim
            size = axis_sizes[axis]
            if size > 1 and shape[dim] % size != 0:
                report.add(Diagnostic(
                    _PASS, "S003", Severity.ERROR, subject,
                    "rule %r shards dim %d of param %r (shape %s) over "
                    "mesh axis %r of size %d, which does not divide %d" %
                    (pattern, dim, name, shape, axis, size, shape[dim]),
                    details={"rule": pattern, "axis": axis, "dim": dim}))

    # -- dead / shadowed rules -------------------------------------------
    for i, (pattern, spec) in enumerate(rule_list):
        if not matches[i]:
            report.add(Diagnostic(
                _PASS, "S005", Severity.WARNING, pattern,
                "dead rule: pattern %r (spec %s) matches none of the "
                "%d params" % (pattern, spec, len(shapes))))
        elif not wins[i]:
            shadowers = sorted({winner_of[n] for n in matches[i]})
            report.add(Diagnostic(
                _PASS, "S006", Severity.WARNING, pattern,
                "shadowed rule: pattern %r matches %s but earlier "
                "rule(s) %s always match first" %
                (pattern, matches[i][:3],
                 [rule_list[j][0] for j in shadowers if j is not None]),
                details={"shadowed_by": [rule_list[j][0]
                                         for j in shadowers
                                         if j is not None]}))

    # -- estimated reshard points (heuristic, INFO) ----------------------
    # group params by their layer (drop the submodule + leaf components:
    # "attn.q_proj.weight" → "attn"); if two params in one group place
    # the SAME mesh axis on DIFFERENT dims, the activations flowing
    # between them likely change layout
    groups: Dict[str, list] = {}
    for name in shapes:
        idx = winner_of[name]
        if idx is None:
            continue
        parts = name.split(".")
        prefix = ".".join(parts[:-2]) if len(parts) > 2 else parts[0]
        groups.setdefault(prefix, []).append(name)
    for prefix, names in sorted(groups.items()):
        placements: Dict[str, Dict[int, str]] = {}
        for name in names:
            _, spec = rule_list[winner_of[name]]
            for dim, axis in _spec_entries(spec):
                placements.setdefault(axis, {})[dim] = name
        for axis, by_dim in sorted(placements.items()):
            if len(by_dim) > 1 and len(set(by_dim.values())) > 1:
                parts = ", ".join("%s@dim%d" % (n, d)
                                  for d, n in sorted(by_dim.items()))
                report.add(Diagnostic(
                    _PASS, "S007", Severity.INFO, prefix,
                    "estimated reshard point in %r: mesh axis %r is "
                    "placed on different dims (%s); expect a layout "
                    "change (or an intentional Megatron column/row "
                    "pair) between these params" % (prefix, axis, parts),
                    details={"axis": axis}))

    return report


register_pass(_PASS)(check_sharding)
