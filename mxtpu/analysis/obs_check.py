"""Observability coverage check (O0xx): fault sites and ledger sites
must resolve to registered telemetry.

The observability layer (docs/observability.md) only earns its keep if
its coverage cannot rot silently: a fault site added to
``resilience.faults.SITES`` without a ``fault.<site>`` entry in the
trace taxonomy would fire events that the tracer REJECTS (downgraded to
``fault.unregistered``), and a CompileLedger site that no metrics
source exposes would vanish from every dashboard.  Mirroring R005 (a
declared fault site no test plan covers), this pass makes both losses
loud:

========  ========  ====================================================
code      severity  meaning
========  ========  ====================================================
``O001``  ERROR     a declared fault site has no registered
                    ``fault.<site>`` trace event type, or a recorded
                    CompileLedger site does not resolve to a
                    ``compile_ledger.<site>.programs`` metrics key (or
                    the registry lost its ``compile_ledger`` source
                    entirely) — observability coverage silently lost
``O002``  INFO      per-run summary (sites checked, event types
                    declared, metrics sources registered)
========  ========  ====================================================

Self-applied in tier-1 via ``python -m mxtpu.analysis all`` (the
``obs`` subcommand runs it alone); red-team fixtures in
tests/test_observability.py assert O001 fires for a site with no event
type and for a registry stripped of its ledger source.
"""

from __future__ import annotations

from typing import Iterable, Optional

from .diagnostics import Diagnostic, Report, Severity, register_pass

__all__ = ["check_observability"]

_PASS = "obs_check"


def check_observability(sites: Optional[Iterable[str]] = None,
                        ledger=None, registry=None,
                        include_summary: bool = False) -> Report:
    """Cross-check the declared fault sites against the trace-event
    taxonomy, and the compile ledger's recorded sites against the
    metrics registry (module docstring).

    sites: override ``resilience.faults.SITES`` (red-team fixtures).
    ledger: a :class:`~mxtpu.analysis.compile_ledger.CompileLedger`
    (default: the live process ledger).  registry: a
    :class:`~mxtpu.observability.metrics.MetricsRegistry` (default: the
    process registry)."""
    from ..observability.trace import EVENT_TYPES

    report = Report()
    if sites is None:
        from ..resilience.faults import SITES as sites
    sites = tuple(sites)
    for site in sites:
        etype = "fault." + site
        if etype not in EVENT_TYPES:
            report.add(Diagnostic(
                _PASS, "O001", Severity.ERROR, site,
                "declared fault site %r has no registered trace event "
                "type %r — a plan firing there would be downgraded to "
                "fault.unregistered and its failure would be invisible "
                "in traces and flight postmortems; add the type to "
                "mxtpu.observability.trace.EVENT_TYPES (or retire the "
                "site)" % (site, etype)))

    if ledger is None:
        from .compile_ledger import get_ledger
        ledger = get_ledger()
    if registry is None:
        from ..observability.metrics import get_registry
        registry = get_registry()
    ledger_sites = ledger.sites()
    if "compile_ledger" not in registry.sources():
        report.add(Diagnostic(
            _PASS, "O001", Severity.ERROR, "compile_ledger",
            "the metrics registry has no 'compile_ledger' source — "
            "every compiled-program count is invisible to snapshot()/"
            "Prometheus exposition; re-register it (see "
            "mxtpu.observability.metrics.default_registry)"))
    else:
        snap = registry.snapshot(sources=("compile_ledger",))
        for site in ledger_sites:
            key = "compile_ledger.%s.programs" % site
            if key not in snap:
                report.add(Diagnostic(
                    _PASS, "O001", Severity.ERROR, site,
                    "compile-ledger site %r does not resolve to the "
                    "metrics key %r — its program count is lost to the "
                    "unified registry (a filtering/replacement of the "
                    "compile_ledger source dropped it)" % (site, key)))

    if include_summary or len(report) == 0:
        report.add(Diagnostic(
            _PASS, "O002", Severity.INFO, "coverage",
            "%d fault site(s) resolve to trace event types; %d ledger "
            "site(s) resolve to metrics keys; %d metrics source(s) "
            "registered" % (len(sites), len(ledger_sites),
                            len(registry.sources()))))
    return report


register_pass(_PASS)(check_observability)
