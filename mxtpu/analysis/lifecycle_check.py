"""lifecycle_check — serving-lifecycle sanitizer (V0xx diagnostics).

ROADMAP item 2 moves replicas out of process, where today's implicit
invariants — every terminal path releases its pages, a COW-shared page
is never written, a drained replica keeps nothing — become remote-state
bugs no single-process test can catch.  This pass family is the
analysis-side counterpart of the engine lifecycle state machine, three
layers deep:

1. **PageSanitizer** — an opt-in shadow state machine
   (``MXTPU_PAGE_SANITIZER=1`` or the :func:`page_sanitizing` context)
   hooked into :class:`~mxtpu.parallel.paging.BlockPool` /
   :class:`~mxtpu.parallel.paging.PrefixIndex` /
   :class:`~mxtpu.parallel.paging.HierarchicalCache` through the
   existing ``on_free`` seam plus alloc/share/pin/spill/restore hooks.
   Every page id is tracked through
   ``free → owned → shared → pinned → spilled → restored → free``;
   an illegal transition raises a typed :class:`PageLifecycleError`
   at the faulting call site carrying the page's full event history
   from a flight-recorder-style ring (counter clock — byte-reproducible
   across reruns).  Unarmed, every hook is a single ``None`` check.
2. **Release-path lint** (:func:`release_path_lint`) — an AST pass
   proving every terminal path in both engines
   (quarantine/expired/failed/cancel/shed/finish/drain) reaches the
   one idempotent release helper; V006 ERROR on a terminal branch that
   does not.  Self-applied over ``mxtpu/parallel/serving.py`` +
   ``mxtpu/serving/`` in tier-1.
3. **Small-scope model checker** (:func:`check_protocol`) —
   exhaustively explores the deterministic gateway/supervisor/router
   state space over bounded configs (≤2 replicas, ≤4 requests, ≤3 QoS
   classes; fault plans from the existing grammar enumerated as
   transition choices), asserting on every trajectory: no request
   stranded, ``blocks_in_use == 0`` ∧ ``pinned_blocks == 0`` after
   drain, no tag dispatched to a dead replica, QoS displacement order.
   V007/V008 ERRORs carry the exact config + fault-plan string, so a
   violation replays bit-identically.

Codes::

    V001  double-free (release of an already-free tracked page)
    V002  use-after-free (gather/write/COW-source naming a freed page)
    V003  write to a shared or pinned page (COW violation)
    V004  pin leak at drain (pinned pages survive a replica drain)
    V005  host-tier orphan (page recycled while its index entry lives)
    V006  terminal path missing the idempotent release helper (lint)
    V007  liveness/accounting violation in the replica-pool model
    V008  protocol violation (dead-replica dispatch, QoS displacement
          order, ReplicaTransport conformance)

See docs/analysis.md "lifecycle_check" and docs/serving.md.
"""

from __future__ import annotations

import ast
import os
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..base import MXTPUError
from ..parallel import paging as _paging
from ..parallel.paging import (BlockPool, HierarchicalCache, NULL_PAGE,
                               PrefixIndex)
from ..resilience.counters import bump as _bump
from .diagnostics import Diagnostic, Report, Severity, register_pass

__all__ = ["PageLifecycleError", "PageSanitizer", "get_sanitizer",
           "page_sanitizing", "release_path_lint", "conformance",
           "check_protocol", "lifecycle_check"]

_PASS = "lifecycle_check"

#: event-ring depth per tracked page (deep enough for a full
#: alloc→share→pin→spill→restore→free story plus slack)
RING_DEPTH = 16


class PageLifecycleError(MXTPUError):
    """An illegal page-lifecycle transition caught by the armed
    :class:`PageSanitizer` — raised at the faulting call site with the
    page's full event history (counter-clock ring, byte-reproducible).
    """

    def __init__(self, code: str, pool_uid: int, bid: int, message: str,
                 history: Tuple[Tuple[int, str, str], ...]):
        self.code = code
        self.pool_uid = pool_uid
        self.bid = bid
        self.history = history
        tail = "".join("\n    #%d %s %s" % ev for ev in history)
        super().__init__(
            "%s: page %d (pool %d): %s — event history (seq op info):%s"
            % (code, bid, pool_uid, message, tail or "\n    (empty)"))


class PageSanitizer:
    """Shadow page-accounting state machine (module docstring).

    One process-wide instance is installed into
    ``mxtpu.parallel.paging._SAN`` when this module imports; the pool
    and index hooks are no-ops until :attr:`armed`.  Shadow state is
    keyed ``(pool_uid, page_id)`` where ``pool_uid`` is assigned lazily
    per pool from the sanitizer's own deterministic counter; page 0
    (the NULL page) and pages allocated before arming are exempt from
    every check, which makes per-test arming safe around module-scoped
    engines.
    """

    def __init__(self):
        self._depth = 0
        self._env = os.environ.get(
            "MXTPU_PAGE_SANITIZER", "") not in ("", "0")
        self._next_uid = 0
        # (pool_uid, bid) -> {"refs": int, "pins": int}; refs == 0 is
        # the tracked-FREE state (what distinguishes a double free from
        # a page this sanitizer never saw allocated)
        self._state: Dict[Tuple[int, int], Dict[str, int]] = {}
        self._rings: Dict[Tuple[int, int], deque] = {}
        # id(index) -> set of page ids it currently references
        self._indexed: Dict[int, set] = {}
        self._seq = 0
        self.transitions = 0
        self.violations = 0          # process-lifetime, never cleared

    # -- arming ----------------------------------------------------------
    @property
    def armed(self) -> bool:
        return self._depth > 0 or self._env

    def enable(self) -> None:
        self._depth += 1

    def disable(self) -> None:
        self._depth = max(0, self._depth - 1)
        if self._depth == 0 and not self._env:
            # full disarm clears shadow state so pages tracked in one
            # test can never false-positive in the next
            self._state.clear()
            self._rings.clear()
            self._indexed.clear()

    def reload_env(self) -> bool:
        """Re-read ``MXTPU_PAGE_SANITIZER`` (parsed once at import)."""
        self._env = os.environ.get(
            "MXTPU_PAGE_SANITIZER", "") not in ("", "0")
        return self._env

    # -- bookkeeping -----------------------------------------------------
    def _uid(self, pool) -> int:
        uid = getattr(pool, "_san_uid", None)
        if uid is None:
            uid = self._next_uid
            self._next_uid += 1
            pool._san_uid = uid
        return uid

    def _event(self, key: Tuple[int, int], op: str, info: str = ""
               ) -> None:
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = deque(maxlen=RING_DEPTH)
        self._seq += 1
        ring.append((self._seq, op, info))
        self.transitions += 1

    def _violate(self, code: str, key: Tuple[int, int], msg: str):
        self.violations += 1
        _bump("lifecycle_violations")
        raise PageLifecycleError(
            code, key[0], key[1], msg,
            tuple(self._rings.get(key, ())))

    def history(self, pool, bid: int) -> Tuple[Tuple[int, str, str], ...]:
        return tuple(self._rings.get((self._uid(pool), int(bid)), ()))

    def stats(self) -> Dict[str, int]:
        """Numeric snapshot (the ``lifecycle.*`` metrics source)."""
        return {
            "armed": int(self.armed),
            "pages_tracked": len(self._state),
            "rings": len(self._rings),
            "transitions": self.transitions,
            "violations_ever": self.violations,
            "indexed_pages": sum(len(s) for s in self._indexed.values()),
        }

    # -- BlockPool hooks -------------------------------------------------
    def note_alloc(self, pool, bids: Sequence[int]) -> None:
        uid = self._uid(pool)
        for bid in bids:
            if bid == NULL_PAGE:
                continue
            key = (uid, int(bid))
            self._state[key] = {"refs": 1, "pins": 0}
            self._event(key, "alloc")

    def note_retain(self, pool, bid: int) -> None:
        key = (self._uid(pool), int(bid))
        st = self._state.get(key)
        if st is None or bid == NULL_PAGE:
            return
        st["refs"] += 1
        self._event(key, "retain", "refs=%d" % st["refs"])

    def note_pin(self, pool, bid: int) -> None:
        key = (self._uid(pool), int(bid))
        st = self._state.get(key)
        if st is None or bid == NULL_PAGE:
            return
        st["refs"] += 1
        st["pins"] += 1
        self._event(key, "pin", "pins=%d" % st["pins"])

    def note_unpin(self, pool, bid: int) -> None:
        key = (self._uid(pool), int(bid))
        st = self._state.get(key)
        if st is None or bid == NULL_PAGE:
            return
        st["pins"] = max(0, st["pins"] - 1)
        self._event(key, "unpin", "pins=%d" % st["pins"])

    def check_release(self, pool, bid: int) -> None:
        """V001 gate at the top of ``BlockPool.release`` — fires BEFORE
        the pool mutates, so the faulting frame is the double-freeing
        caller."""
        if bid == NULL_PAGE:
            return
        key = (self._uid(pool), int(bid))
        st = self._state.get(key)
        if st is None:          # allocated before arming: exempt
            return
        if st["refs"] <= 0:
            self._event(key, "release", "double-free")
            self._violate(
                "V001", key,
                "double free: release() of a page already returned to "
                "the free list")

    def note_release(self, pool, bid: int, freed: bool) -> None:
        if bid == NULL_PAGE:
            return
        key = (self._uid(pool), int(bid))
        st = self._state.get(key)
        if st is None:
            return
        st["refs"] = max(0, st["refs"] - 1)
        self._event(key, "free" if freed else "release",
                    "refs=%d" % st["refs"])
        if freed:
            st["refs"] = 0
            st["pins"] = 0
            self._check_recycled(pool, key)

    def _check_recycled(self, pool, key: Tuple[int, int]) -> None:
        """V005: the pool's own index (its ``on_free`` hook target)
        still references this just-recycled page — the erase the
        ``on_free`` seam exists to guarantee did not happen."""
        owner = getattr(getattr(pool, "_on_free", None), "__self__", None)
        if isinstance(owner, PrefixIndex):
            entries = self._indexed.get(id(owner))
            if entries and key[1] in entries:
                self._violate(
                    "V005", key,
                    "host-tier orphan: page recycled while its prefix-"
                    "index entry survives (index erase skipped)")

    def check_use(self, pool, bid: int, write: bool = False) -> None:
        """V002 (any use of a freed page) / V003 (write to a shared or
        pinned page) — the engine's ``_read_page`` / ``_write_page``
        gate."""
        if bid == NULL_PAGE:
            return
        key = (self._uid(pool), int(bid))
        st = self._state.get(key)
        if st is None:
            return
        op = "write" if write else "gather"
        if st["refs"] <= 0:
            self._event(key, op, "use-after-free")
            self._violate(
                "V002", key,
                "use after free: %s names a recycled page" % op)
        if write and (st["refs"] > 1 or st["pins"] > 0):
            self._event(key, op, "refs=%d pins=%d"
                        % (st["refs"], st["pins"]))
            self._violate(
                "V003", key,
                "write to a shared/pinned page (refs=%d, pins=%d) — "
                "copy-on-write violation" % (st["refs"], st["pins"]))
        self._event(key, op)

    def note_cow(self, pool, src: int, dst: int) -> None:
        """COW gate at the paged engine's clone: the donor must still be
        allocated (V002) and the clone target solely owned (V003)."""
        if src != NULL_PAGE:
            skey = (self._uid(pool), int(src))
            st = self._state.get(skey)
            if st is not None and st["refs"] <= 0:
                self._event(skey, "cow-src", "use-after-free")
                self._violate(
                    "V002", skey,
                    "use after free: COW donor page was recycled")
            if st is not None:
                self._event(skey, "cow-src", "dst=%d" % dst)
        if dst != NULL_PAGE:
            dkey = (self._uid(pool), int(dst))
            st = self._state.get(dkey)
            if st is not None:
                if st["refs"] != 1 or st["pins"] > 0:
                    self._event(dkey, "cow-dst", "refs=%d pins=%d"
                                % (st["refs"], st["pins"]))
                    self._violate(
                        "V003", dkey,
                        "COW clone into a page that is not solely "
                        "owned (refs=%d, pins=%d)"
                        % (st["refs"], st["pins"]))
                self._event(dkey, "cow-dst", "src=%d" % src)

    def note_spill(self, pool, bids: Sequence[int]) -> None:
        uid = self._uid(pool)
        for bid in bids:
            key = (uid, int(bid))
            if key in self._state:
                self._event(key, "spill")

    def note_restore(self, pool, bids: Sequence[int]) -> None:
        uid = self._uid(pool)
        for bid in bids:
            key = (uid, int(bid))
            if key in self._state:
                self._event(key, "restore")

    def check_drain(self, pool) -> None:
        """V004: a replica drain left pinned pages behind — after drain
        a replica may hold zero pages (the transport contract)."""
        uid = self._uid(pool)
        leaked = sorted(bid for (u, bid), st in self._state.items()
                        if u == uid and st["pins"] > 0)
        if leaked:
            key = (uid, leaked[0])
            self._event(key, "drain", "pin-leak x%d" % len(leaked))
            self._violate(
                "V004", key,
                "pin leak at drain: %d page(s) still pinned after the "
                "replica drained (%r)" % (len(leaked), leaked))

    # -- PrefixIndex hooks -----------------------------------------------
    def note_register(self, index, bid: int) -> None:
        self._indexed.setdefault(id(index), set()).add(int(bid))

    def note_evict(self, index, bid: int) -> None:
        entries = self._indexed.get(id(index))
        if entries is not None:
            entries.discard(int(bid))


#: the process-wide sanitizer, installed into the paging module's
#: ``_SAN`` hook point (paging imports nothing from analysis, so this
#: direction is cycle-free)
_SANITIZER = PageSanitizer()
_paging._SAN = _SANITIZER


def get_sanitizer() -> PageSanitizer:
    return _SANITIZER


class page_sanitizing:
    """Context manager arming the page sanitizer::

        with page_sanitizing():
            engine.run()   # any lifecycle bug raises PageLifecycleError

    Re-entrant; restores the prior armed state on exit, and a full
    disarm clears all shadow state (cross-test hygiene)."""

    def __enter__(self) -> PageSanitizer:
        _SANITIZER.enable()
        return _SANITIZER

    def __exit__(self, exc_type, exc, tb):
        _SANITIZER.disable()
        return False


# =====================================================================
# Layer 2: release-path lint (V006)
# =====================================================================

#: calls that count as reaching the idempotent release path after a
#: slot is abandoned (``self._slots[i] = None``)
_RELEASE_FOLLOWERS = frozenset({
    "_scrub_row", "_release_row", "_finish", "_quarantine_request",
    "_requeue_or_fail"})

#: terminal status literals whose assignment must be paired with the
#: gateway's bounded terminal bookkeeping
_TERMINAL_STATUSES = frozenset({"ok", "failed", "expired", "shed"})

#: calls that count as terminal bookkeeping for a ``.status`` assign
_DONE_FOLLOWERS = frozenset({"_mark_done", "_finish_shed", "_resolve"})


def _calls_in(node: ast.AST) -> set:
    """Attribute/function names called anywhere under ``node``."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute):
                out.add(f.attr)
            elif isinstance(f, ast.Name):
                out.add(f.id)
    return out


def _is_slot_clear(stmt: ast.stmt) -> Optional[ast.Assign]:
    """``self._slots[...] = None`` (or ``x._slots[...] = None``)."""
    if not isinstance(stmt, ast.Assign):
        return None
    if not (isinstance(stmt.value, ast.Constant)
            and stmt.value.value is None):
        return None
    for tgt in stmt.targets:
        if (isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Attribute)
                and tgt.value.attr == "_slots"):
            return stmt
    return None


def _blocks(node: ast.AST):
    """Yield every statement list under ``node`` (bodies, orelse,
    finally, handlers) — the unit rule (b) checks followers within."""
    for sub in ast.walk(node):
        for field in ("body", "orelse", "finalbody"):
            block = getattr(sub, field, None)
            if isinstance(block, list) and block and \
                    isinstance(block[0], ast.stmt):
                yield block


def _lint_release_paths(tree: ast.AST, filename: str, report: Report
                        ) -> None:
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        # (a) engines with a dedicated release helper must reach it
        # from both scrub and finish
        if "_release_row" in methods:
            for name in ("_scrub_row", "_finish"):
                m = methods.get(name)
                if m is not None and \
                        "_release_row" not in _calls_in(m):
                    report.add(
                        _PASS, "V006", Severity.ERROR,
                        "%s.%s" % (cls.name, name),
                        "terminal path does not reach the idempotent "
                        "release helper _release_row()",
                        location="%s:%d" % (filename, m.lineno))
        # (c) a transport implementation's drain must drop both cache
        # tiers (stub bodies — docstring + raise — are the protocol).
        # A cross-process transport discharges the obligation at the
        # seam instead: the worker-side adapter's drain runs drop_cache
        # (reached via an ``_rpc("drain")`` call), and on the failure
        # path ``_kill_worker`` ends the address space holding the
        # pages — either delegation is as page-zero as a local drop.
        if "drain" in methods and "cancel" in methods:
            m = methods["drain"]
            real = [s for s in m.body
                    if not (isinstance(s, ast.Expr)
                            and isinstance(s.value, ast.Constant))]
            calls = _calls_in(m)
            delegated = "_kill_worker" in calls or any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "_rpc"
                and c.args
                and isinstance(c.args[0], ast.Constant)
                and c.args[0].value == "drain"
                for c in ast.walk(m))
            if real and not all(isinstance(s, ast.Raise) for s in real) \
                    and "drop_cache" not in calls and not delegated:
                report.add(
                    _PASS, "V006", Severity.ERROR,
                    "%s.drain" % cls.name,
                    "transport drain() does not drop the engine cache "
                    "tiers (drop_cache) — a drained replica must hold "
                    "zero pages",
                    location="%s:%d" % (filename, m.lineno))
        for mname, m in methods.items():
            # (b) an abandoned slot must reach a release follower (or
            # re-raise; _finish IS the follower for its own tail)
            if mname not in ("_finish",):
                for block in _blocks(m):
                    for i, stmt in enumerate(block):
                        if _is_slot_clear(stmt) is None:
                            continue
                        rest = block[i + 1:]
                        ok = any(isinstance(s, ast.Raise) for s in rest)
                        for s in rest:
                            if _calls_in(s) & _RELEASE_FOLLOWERS:
                                ok = True
                                break
                        if not ok:
                            report.add(
                                _PASS, "V006", Severity.ERROR,
                                "%s.%s" % (cls.name, mname),
                                "slot abandoned (self._slots[...] = "
                                "None) with no release call on the "
                                "path (%s)"
                                % ", ".join(sorted(_RELEASE_FOLLOWERS)),
                                location="%s:%d"
                                % (filename, stmt.lineno))
            # (d) a terminal status assignment needs the bounded
            # terminal bookkeeping in the same method
            hits = [
                s for s in ast.walk(m)
                if isinstance(s, ast.Assign)
                and isinstance(s.value, ast.Constant)
                and s.value.value in _TERMINAL_STATUSES
                and any(isinstance(t, ast.Attribute)
                        and t.attr == "status" for t in s.targets)]
            if hits and not (_calls_in(m) & _DONE_FOLLOWERS):
                report.add(
                    _PASS, "V006", Severity.ERROR,
                    "%s.%s" % (cls.name, mname),
                    "terminal status %r assigned without terminal "
                    "bookkeeping (%s)"
                    % (hits[0].value.value,
                       ", ".join(sorted(_DONE_FOLLOWERS))),
                    location="%s:%d" % (filename, hits[0].lineno))


def _default_lint_paths() -> List[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(pkg, "parallel", "serving.py")]
    sdir = os.path.join(pkg, "serving")
    if os.path.isdir(sdir):
        paths.extend(sorted(
            os.path.join(sdir, f) for f in os.listdir(sdir)
            if f.endswith(".py")))
    return paths


def release_path_lint(paths: Optional[Sequence[str]] = None,
                      source: Optional[str] = None,
                      filename: str = "<source>") -> Report:
    """V006: prove every terminal path reaches the idempotent release
    helper.  ``source`` lints one in-memory module (the red-team
    fixtures); otherwise ``paths`` (default: both engines and the
    serving package)."""
    report = Report()
    if source is not None:
        _lint_release_paths(ast.parse(source, filename), filename, report)
        return report
    for path in (paths if paths is not None else _default_lint_paths()):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                text = fh.read()
            tree = ast.parse(text, path)
        except (OSError, SyntaxError) as exc:
            report.add(_PASS, "V006", Severity.WARNING, path,
                       "cannot lint: %s" % exc, location=path)
            continue
        _lint_release_paths(tree, os.path.basename(path), report)
    return report


# =====================================================================
# Layer 3: small-scope model checking (V007/V008) + conformance
# =====================================================================

#: the ReplicaTransport surface a conforming transport must implement
PROTOCOL_SURFACE = ("capacity", "load", "free_slots", "prefix_probe",
                    "submit", "step", "poll", "health", "progress",
                    "cancel", "drain")


def conformance(cls, report: Optional[Report] = None) -> Report:
    """Structural ReplicaTransport conformance: every protocol member
    must be overridden from the raising base stubs (V008)."""
    from ..serving.transport import ReplicaTransport
    report = report if report is not None else Report()
    missing = [name for name in PROTOCOL_SURFACE
               if getattr(cls, name, None)
               is getattr(ReplicaTransport, name)]
    if missing:
        report.add(
            _PASS, "V008", Severity.ERROR, cls.__name__,
            "ReplicaTransport conformance: %d protocol member(s) not "
            "implemented: %s" % (len(missing), ", ".join(missing)),
            details={"missing": missing})
    return report


def _make_model_replica():
    """Define the pure-host bounded-state replica lazily (keeps module
    import free of the serving package until a checker runs)."""
    from ..resilience.faults import inject as _inject
    from ..serving.transport import ReplicaDownError, ReplicaTransport

    class _ModelReplica(ReplicaTransport):
        """Small-scope model of one replica: decodes one token per
        request per step, page-accounts with a real BlockPool, honors
        the ``replica.*`` fault sites — and compiles NOTHING.  The
        checker's whole state space is host counters."""

        def __init__(self, replica_id: str = "r0", capacity: int = 2,
                     pool_pages: int = 8, block_size: int = 4):
            self.replica_id = str(replica_id)
            self.alive = True
            self._cap = int(capacity)
            self._bp = BlockPool(pool_pages, block_size)
            self._live: Dict[Any, Dict[str, Any]] = {}
            self._order: List[Any] = []
            self._steps = 0
            self._out = 0
            self._done = 0
            #: V008 evidence: tags submitted while ``alive`` was False
            self.dead_submits: List[Any] = []

        @property
        def capacity(self) -> int:
            return self._cap

        @property
        def load(self) -> int:
            return len(self._live)

        @property
        def free_slots(self) -> int:
            return max(0, self._cap - len(self._live))

        def prefix_probe(self, prompt) -> int:
            return 0

        def submit(self, spec: dict, tag) -> Any:
            if not self.alive:
                self.dead_submits.append(tag)
                raise ReplicaDownError(
                    "model replica %s is down" % self.replica_id)
            pages = self._bp.alloc(1)
            self._live[tag] = {
                "pages": pages,
                "left": int(spec["max_new_tokens"]),
                "n": 0, "new": []}
            self._order.append(tag)
            return tag

        def step(self) -> None:
            if not self._live:
                return
            self._steps += 1
            for st in self._live.values():
                if st["left"] > 0:
                    st["left"] -= 1
                    st["new"].append((st["n"] * 3 + 1) % 7)
                    st["n"] += 1
                    self._out += 1

        def _retire(self, tag) -> None:
            st = self._live.pop(tag, None)
            if st is None:
                return
            for bid in st["pages"]:
                self._bp.release(bid)
            self._order.remove(tag)
            self._done += 1

        def poll(self):
            _inject("replica.stream", key=self.replica_id)
            tokens: Dict[Any, List[int]] = {}
            finished: List[Tuple[Any, str, Any, Any]] = []
            for tag in list(self._order):
                st = self._live[tag]
                if st["new"]:
                    tokens[tag] = st["new"]
                    st["new"] = []
                if st["left"] <= 0:
                    finished.append((tag, "ok", None, None))
                    self._retire(tag)
            return tokens, finished, []

        def health(self) -> None:
            _inject("replica.health", key=self.replica_id)

        def progress(self) -> tuple:
            return (self._steps, self._out, self._done)

        def cancel(self, tag) -> bool:
            if tag in self._live:
                self._retire(tag)
                return True
            return False

        def drain(self) -> List[Any]:
            tags = list(self._order)
            for tag in tags:
                self._retire(tag)
            return tags

    return _ModelReplica


_MODEL_REPLICA = None


def model_replica_cls():
    """The checker's pure-host replica class (lazily defined)."""
    global _MODEL_REPLICA
    if _MODEL_REPLICA is None:
        _MODEL_REPLICA = _make_model_replica()
    return _MODEL_REPLICA


def _shed_observer(gateway_cls):
    """Subclass ``gateway_cls`` recording every displacement decision
    with its queue snapshot — pure observation, behavior unchanged."""

    class _Observed(gateway_cls):
        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self.shed_log: List[Tuple[Any, int, List[Tuple[int, int]]]] \
                = []

        def _pick_shed_victim(self, incoming_qos):
            victim = super()._pick_shed_victim(incoming_qos)
            snapshot = [(self._reqs[r].qos, r) for r in self._queue]
            self.shed_log.append((victim, incoming_qos, snapshot))
            return victim

    _Observed.__name__ = "_Observed" + gateway_cls.__name__
    return _Observed


#: the bounded fault plans the checker enumerates as transition
#: choices — every plan is bit-replayable by the grammar's contract
DEFAULT_FAULT_PLANS = (
    "",
    "replica.health#r0@1x3:raise",
    "replica.stream#r0@2x3:raise",
    "router.dispatch@1x1:raise",
    "gateway.admit#1@1:raise",
)


def check_protocol(replica_factory=None, gateway_cls=None,
                   fault_plans: Optional[Sequence[str]] = None,
                   replica_counts: Sequence[int] = (1, 2),
                   qos_classes: Sequence[int] = (1, 3),
                   n_requests: int = 4,
                   max_pending: int = 2,
                   max_new_tokens: int = 3) -> Report:
    """Small-scope model check of the gateway/supervisor/router stack
    (module docstring).  Bounded configs × fault plans are enumerated
    as deterministic trajectories; every violation diagnostic carries
    the exact ``(config, fault_plan)`` coordinates, so re-running the
    same call replays it bit-identically.

    ``replica_factory(replica_id) -> ReplicaTransport`` and
    ``gateway_cls`` let the red-team fixtures inject defective
    implementations; the defaults model-check the REAL service layer
    over the pure-host :func:`model_replica_cls`.
    """
    import numpy as onp

    from ..resilience import QosShedError
    from ..resilience.faults import InjectedFault, fault_plan
    from ..serving.gateway import Gateway

    report = Report()
    factory = replica_factory if replica_factory is not None \
        else model_replica_cls()
    observed_cls = _shed_observer(
        gateway_cls if gateway_cls is not None else Gateway)
    plans = tuple(fault_plans) if fault_plans is not None \
        else DEFAULT_FAULT_PLANS
    n_requests = min(int(n_requests), 4)
    prompt = onp.asarray([[1, 2, 3, 4]], dtype=onp.int32)

    def _fail(code, subject, msg, cfg, plan, **details):
        report.add(_PASS, code, Severity.ERROR, subject, msg,
                   details=dict(details, config=cfg, fault_plan=plan))

    for n_rep in replica_counts:
        for qos_n in qos_classes:
            for plan in plans:
                cfg = {"replicas": int(n_rep), "qos_classes": int(qos_n),
                       "requests": n_requests,
                       "max_pending": int(max_pending)}
                label = ("replicas=%d qos=%d plan=%r"
                         % (n_rep, qos_n, plan))
                reps = [factory("r%d" % i) for i in range(int(n_rep))]
                gw = observed_cls(
                    reps, qos_classes=int(qos_n),
                    max_pending=int(max_pending),
                    hedge_fraction=None, fail_threshold=3,
                    stall_ticks=None, revive_after_ticks=2)
                rids: List[int] = []
                with fault_plan(plan, sleep=lambda s: None):
                    for i in range(n_requests):
                        try:
                            rids.append(gw.submit(
                                prompt, max_new_tokens,
                                qos=i % int(qos_n)))
                        except (QosShedError, InjectedFault):
                            continue   # sheds/poisoned admits are
                            #            legal terminal outcomes
                    stranded: Optional[str] = None
                    outages = 0
                    while True:
                        try:
                            gw.run()
                            break
                        except MXTPUError as exc:   # before RuntimeError
                            #                         (its base class)
                            # pool-wide outage: the gateway's typed
                            # signal to revive or rebuild.  Model the
                            # operator revival (bounded) — liveness
                            # then demands the requeued work completes.
                            outages += 1
                            if outages > 3:
                                stranded = "MXTPUError: %s" % exc
                                break
                            for rep in gw.supervisor.replicas:
                                if not rep.alive:
                                    gw.supervisor.revive(rep.replica_id)
                        except RuntimeError as exc:
                            stranded = "RuntimeError: %s" % exc
                            break
                # -- liveness: every admitted request went terminal ---
                if stranded is not None:
                    _fail("V007", label,
                          "liveness: gateway.run() did not converge "
                          "(%s)" % stranded, cfg, plan)
                else:
                    hung = [r for r in rids
                            if not gw._reqs[r].terminal]
                    if hung:
                        _fail("V007", label,
                              "liveness: request(s) %r stranded "
                              "non-terminal after run()" % hung,
                              cfg, plan, stranded_rids=hung)
                # -- page accounting: drain leaves nothing ------------
                for rep in reps:
                    rep.drain()
                    pool = getattr(rep, "_bp", None)
                    if pool is None:
                        continue
                    if pool.in_use != 0 or pool.pinned_count != 0:
                        _fail("V007",
                              "%s %s" % (label, rep.replica_id),
                              "page accounting after drain: "
                              "blocks_in_use=%d pinned_blocks=%d "
                              "(both must be 0)"
                              % (pool.in_use, pool.pinned_count),
                              cfg, plan, replica=rep.replica_id,
                              in_use=pool.in_use,
                              pinned=pool.pinned_count)
                # -- no tag dispatched to a dead replica ---------------
                for rep in reps:
                    dead = getattr(rep, "dead_submits", None)
                    # a ReplicaDownError-raising refusal is the
                    # transport contract; observing MANY of them means
                    # the router kept targeting a known-dead replica
                    if dead and len(dead) > len(rids):
                        _fail("V008",
                              "%s %s" % (label, rep.replica_id),
                              "%d submit(s) reached replica %s while "
                              "it was declared dead"
                              % (len(dead), rep.replica_id),
                              cfg, plan, replica=rep.replica_id,
                              dead_submits=len(dead))
                # -- QoS displacement order ---------------------------
                for victim, incoming, snapshot in gw.shed_log:
                    eligible = [(q, r) for q, r in snapshot
                                if q > incoming]
                    want = max(eligible)[1] if eligible else None
                    if victim != want:
                        _fail("V008", label,
                              "QoS displacement order: shed victim %r, "
                              "expected %r (newest request of the "
                              "lowest class below the incoming one)"
                              % (victim, want),
                              cfg, plan, victim=victim, expected=want,
                              queue=[list(t) for t in snapshot])
    return report


# =====================================================================
# The registered pass
# =====================================================================

def _sanitizer_self_drive(report: Report) -> None:
    """Drive a pure-host pool/index/cache through the full lifecycle
    under arming; a PageLifecycleError here is a V0xx ERROR against the
    in-repo paging layer itself."""
    try:
        with page_sanitizing() as san:
            idx = PrefixIndex(4)
            pool = BlockPool(8, 4, on_free=idx.evict)
            hc = HierarchicalCache(pool, idx, pin_blocks=4,
                                   host_blocks=4)
            toks = tuple(range(8))
            pages = pool.alloc(2)
            idx.register(toks, pages)
            chain = hc.pin_chain(toks, pages)
            for bid in pages:
                pool.release(bid)       # table drops; pins hold
            pool.retain(pages[0])       # a share
            pool.release(pages[0])
            hc.spill(chain, ["p0", "p1"])   # device → host tier
            restored = pool.alloc(2)        # host → device restore
            san.note_restore(pool, restored)
            idx.register(toks, restored)
            chain2 = hc.pin_chain(toks, restored)
            for bid in restored:
                pool.release(bid)
            host = hc.host_match(toks, 8)
            if host is not None:
                hc.drop_host(host[0])
            hc.drop_chain(chain2)           # drain
            san.check_drain(pool)
            if pool.in_use != 0:
                report.add(_PASS, "V007", Severity.ERROR,
                           "sanitizer-self-drive",
                           "self-drive left %d page(s) allocated"
                           % pool.in_use)
    except PageLifecycleError as exc:
        report.add(_PASS, exc.code, Severity.ERROR,
                   "sanitizer-self-drive", str(exc))


@register_pass(_PASS)
def lifecycle_check(paths: Optional[Sequence[str]] = None) -> Report:
    """The registered pass: release-path lint over the engines and the
    serving package (V006), ReplicaTransport conformance + a bounded
    model-check sweep of the real service stack (V007/V008), and an
    armed sanitizer self-drive over the paging layer (V001–V005).
    Entirely host-side — compiles nothing."""
    report = release_path_lint(paths)
    _sanitizer_self_drive(report)
    try:
        from ..serving.transport import InProcessReplica
        conformance(InProcessReplica, report)
        conformance(model_replica_cls(), report)
        report.extend(check_protocol(
            replica_counts=(1, 2), qos_classes=(1, 3)))
    except ImportError as exc:   # serving stack unavailable: degrade
        report.add(_PASS, "V008", Severity.WARNING, "serving",
                   "model check skipped: %s" % exc)
    return report
