"""memory_estimate: sharding-aware per-device HBM cost model.

The reference ran nnvm's PlanMemory pass — static buffer assignment over
the graph before execution; on TPU the analogous question is "does this
program fit in HBM per device, under this PartitionSpec/mesh?" and the
answer usually arrives as an opaque RESOURCE_EXHAUSTED deep inside the
first compile.  This pass answers it statically:

- **Symbol graphs** (:func:`estimate_graph_memory`): reuses
  ``Symbol._propagate`` — the same shape/dtype propagation walk
  ``verify_graph`` uses — then runs a liveness scan over the topological
  schedule: params + inputs resident throughout, each op output live
  from its def to its last consumer, graph outputs live to the end.
- **Jittable callables** (:func:`estimate_jit_memory`): the same
  liveness scan over the ``jax.make_jaxpr`` equation list (call-like
  sub-jaxprs — pjit, remat, custom_vjp — contribute their inner peak
  while executing), which covers CachedOp-style compiled programs,
  decode steps with KV caches, and trainer steps.
- **KV caches** (:func:`kv_cache_residency`): persistent cache bytes for
  a block's ``init_cache`` under a cache PartitionSpec, abstractly
  evaluated (no allocation).  :func:`paged_kv_cache_residency` prices
  the BLOCK-PAGED layout (PagedContinuousBatchingEngine): bytes per
  page, pages resident vs free, and the bytes cross-request prefix
  sharing is saving — refcounted pages are priced ONCE, not
  per-request, which is what a ``check_memory`` budget over the paged
  pool inherits for free (the pool is one allocation whatever the
  sharing degree).

Per-device accounting: a tensor matched to a PartitionSpec divides by
the product of the mesh-axis sizes it is sharded over (ceil per dim —
GSPMD's padding rule).  Intermediates are counted replicated unless the
caller provides specs — an upper bound, which is the safe direction for
a fit check.  The estimator is cross-checked against
``jax.jit(...).lower().compile().memory_analysis()`` on CPU in
tests/test_memory_estimate.py (within 10% on the reference graphs).

Diagnostics (pass name ``memory_estimate``; M0xx):

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
M001        ERROR     estimated per-device bytes exceed the budget
M002        WARNING   estimate within budget but above the headroom
                      fraction (default 90%) — one growth step from OOM
M003        INFO      accounting breakdown (params / inputs / activations
                      peak / kv cache / outputs), always emitted
M004        INFO      top liveness contributors (largest intermediates)
M005        WARNING   nodes whose shapes could not be inferred — the
                      estimate is a LOWER bound
M006        ERROR     host-RAM KV tier exceeds its host budget (the
                      hierarchical cache's spilled chains live in host
                      memory, never HBM — they are budgeted separately)
M007        INFO      per-grid-step VMEM pricing of a Pallas kernel call
                      (emitted by the ``kernel_check`` pass from
                      :func:`kernel_vmem_estimate` — the on-chip sibling
                      of the M003 HBM breakdown)
==========  ========  =====================================================

Beside the HBM model this module also prices **VMEM** — the ~16 MiB
on-chip budget every Pallas grid step must fit in
(:func:`kernel_vmem_estimate`, consumed by
:mod:`mxtpu.analysis.kernel_check` for its K003 verdict).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .diagnostics import Diagnostic, Report, Severity, register_pass

__all__ = ["MemoryEstimate", "estimate_graph_memory", "estimate_jit_memory",
           "kv_cache_residency", "paged_kv_cache_residency", "check_memory",
           "xla_memory_stats", "parse_bytes", "format_bytes",
           "LANE", "sublane_tile", "kernel_vmem_estimate",
           "kernel_hbm_traffic"]

_PASS = "memory_estimate"

# variables with these suffixes are parameters (resident weights), the
# rest are data inputs — accounting split only; both are resident
_PARAM_SUFFIXES = ("weight", "bias", "gamma", "beta", "moving_mean",
                   "moving_var", "running_mean", "running_var")


class MemoryEstimate:
    """Per-device byte accounting for one program/graph."""

    __slots__ = ("param_bytes", "input_bytes", "activation_peak_bytes",
                 "output_bytes", "kv_cache_bytes", "contributors",
                 "unknown_nodes", "n_values")

    def __init__(self):
        self.param_bytes = 0
        self.input_bytes = 0
        self.activation_peak_bytes = 0   # peak live intermediates+outputs
        self.output_bytes = 0
        self.kv_cache_bytes = 0
        self.contributors: List[Tuple[str, int]] = []
        self.unknown_nodes: List[str] = []
        self.n_values = 0

    @property
    def total_bytes(self) -> int:
        """Peak per-device residency: resident tensors (params, inputs,
        KV caches) plus the activation-liveness peak (which includes the
        outputs at schedule end)."""
        return (self.param_bytes + self.input_bytes + self.kv_cache_bytes
                + self.activation_peak_bytes)

    def breakdown(self) -> Dict[str, int]:
        return {"params": self.param_bytes, "inputs": self.input_bytes,
                "kv_cache": self.kv_cache_bytes,
                "activation_peak": self.activation_peak_bytes,
                "outputs": self.output_bytes,
                "total": self.total_bytes}

    def __repr__(self):
        return "<MemoryEstimate %s>" % ", ".join(
            "%s=%s" % (k, format_bytes(v))
            for k, v in self.breakdown().items())


# -- byte helpers ---------------------------------------------------------

def format_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return ("%d%s" % (n, unit) if unit == "B"
                    else "%.2f%s" % (n, unit))
        n = n / 1024
    return str(n)


def parse_bytes(text) -> int:
    """'8GB' / '512MiB' / '1e9' → bytes (decimal suffixes are power-of-
    1024 too: HBM budgets are conventionally binary)."""
    if isinstance(text, (int, float)):
        return int(text)
    s = str(text).strip().lower()
    mult = 1
    for suf, m in (("tib", 1024 ** 4), ("tb", 1024 ** 4),
                   ("gib", 1024 ** 3), ("gb", 1024 ** 3),
                   ("mib", 1024 ** 2), ("mb", 1024 ** 2),
                   ("kib", 1024), ("kb", 1024), ("b", 1)):
        if s.endswith(suf):
            mult = m
            s = s[:-len(suf)].strip()
            break
    return int(float(s) * mult)


def _axis_sizes(mesh) -> Dict[str, int]:
    if mesh is None:
        return {}
    sizes = getattr(mesh, "axis_sizes", None)
    if sizes is not None:
        return dict(sizes)
    if isinstance(mesh, dict):
        return dict(mesh)
    names = getattr(mesh, "axis_names", None)
    devs = getattr(mesh, "devices", None)
    if names is not None and devs is not None:
        return dict(zip(names, devs.shape))
    return {}


def _itemsize(dtype) -> int:
    import jax.numpy as jnp
    try:
        return jnp.dtype(dtype).itemsize
    except TypeError:
        return 4


def _sharded_nbytes(shape, dtype, spec, axis_sizes) -> int:
    """Per-device bytes of a tensor under a PartitionSpec (ceil per
    sharded dim — GSPMD pads uneven shards)."""
    n = _itemsize(dtype)
    for i, dim in enumerate(shape):
        shards = 1
        if spec is not None and i < len(spec) and spec[i] is not None:
            axes = spec[i] if isinstance(spec[i], tuple) else (spec[i],)
            for a in axes:
                shards *= axis_sizes.get(a, 1)
        n *= math.ceil(dim / shards) if shards > 1 else dim
    return n


# -- Symbol-graph path ----------------------------------------------------

def estimate_graph_memory(sym, known_shapes: Optional[dict] = None,
                          rules=None, mesh=None,
                          kv_caches: Sequence[Tuple[tuple, Any]] = (),
                          params: Optional[set] = None,
                          **shape_kwargs) -> MemoryEstimate:
    """Estimate per-device memory of a Symbol graph.

    known_shapes/**shape_kwargs: input shapes (``infer_shape``
    convention).  rules: a ShardingRules mapping variable names to
    PartitionSpecs (params divide by their shard count); mesh: DeviceMesh
    / jax Mesh / ``{axis: size}`` dict.  kv_caches: extra persistent
    (shape, dtype) residents (use :func:`kv_cache_residency` to derive
    them from a block).  params: explicit set of variable names to count
    as parameters; default is the ``_weight``/``_bias``/... suffix
    heuristic (classification only affects the breakdown, not the
    total).
    """
    est = MemoryEstimate()
    known = dict(known_shapes or {})
    known.update(shape_kwargs)
    axis_sizes = _axis_sizes(mesh)

    res = sym._propagate(known)
    topo = sym._topo()

    # resident graph inputs
    for node in topo:
        if node.op is not None:
            continue
        shape = res.var_shapes.get(node.name)
        if shape is None:
            est.unknown_nodes.append(node.name)
            continue
        dt = res.dtypes.get((id(node), 0), "float32")
        spec = None
        if rules is not None:
            try:
                spec = rules.spec_for(node.name, len(shape))
            except ValueError:
                spec = None
        nbytes = _sharded_nbytes(shape, dt, spec, axis_sizes)
        is_param = (node.name in params if params is not None
                    else node.name.endswith(_PARAM_SUFFIXES))
        if is_param:
            est.param_bytes += nbytes
        else:
            est.input_bytes += nbytes

    for shape, dt in kv_caches:
        est.kv_cache_bytes += _sharded_nbytes(tuple(shape), dt, None,
                                              axis_sizes)

    # liveness over the op schedule
    schedule = [n for n in topo if n.op is not None]
    order = {id(n): i for i, n in enumerate(schedule)}
    last_use: Dict[Tuple[int, int], int] = {}
    for n in schedule:
        for s in n.inputs:
            if s._node.op is None:
                continue  # inputs are resident, not liveness-tracked
            key = (id(s._node), s._index)
            last_use[key] = max(last_use.get(key, -1), order[id(n)])
    out_entries = set()
    for n, i in sym._output_entries():
        if n.op is not None:
            out_entries.add((id(n), i))
            last_use[(id(n), i)] = len(schedule)  # live to the end

    sizes: Dict[Tuple[int, int], int] = {}
    names: Dict[Tuple[int, int], str] = {}
    for n in schedule:
        for i in range(n.num_outputs):
            key = (id(n), i)
            shape = res.shapes.get(key)
            if shape is None:
                if n.name not in est.unknown_nodes:
                    est.unknown_nodes.append(n.name)
                continue
            dt = res.dtypes.get(key, "float32")
            sizes[key] = _sharded_nbytes(shape, dt, None, axis_sizes)
            names[key] = n.name if n.num_outputs == 1 \
                else "%s[%d]" % (n.name, i)

    live: Dict[Tuple[int, int], int] = {}
    running = 0
    peak = 0
    peak_set: List[Tuple[str, int]] = []
    for step, n in enumerate(schedule):
        for i in range(n.num_outputs):
            key = (id(n), i)
            if key in sizes and key not in live and \
                    last_use.get(key, -1) >= step:
                live[key] = sizes[key]
                running += sizes[key]
        if running > peak:
            peak = running
            peak_set = sorted(((names[k], v) for k, v in live.items()),
                              key=lambda kv: -kv[1])[:8]
        for key in [k for k, lu in last_use.items()
                    if lu == step and k in live]:
            running -= live.pop(key)

    est.activation_peak_bytes = peak
    est.output_bytes = sum(sizes.get(k, 0) for k in out_entries)
    est.contributors = peak_set
    est.n_values = len(sizes)
    return est


# -- jaxpr path -----------------------------------------------------------

_CALL_PRIMITIVES = {"pjit", "closed_call", "core_call", "xla_call",
                    "named_call", "custom_jvp_call", "custom_vjp_call",
                    "custom_vjp_call_jaxpr", "remat", "remat2",
                    "checkpoint", "custom_lin"}


def _inner_jaxpr(eqn):
    p = eqn.params
    for k in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        j = p.get(k)
        if j is not None:
            return getattr(j, "jaxpr", j)
    return None


def _aval_nbytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = _itemsize(getattr(aval, "dtype", "float32"))
    for d in shape:
        n *= int(d)
    return n


# pure-layout primitives: same-bytes views XLA fuses into the consumer
# (or bitcasts) instead of materializing — their outputs alias the input
_LAYOUT_PRIMS = {"transpose", "reshape", "squeeze", "expand_dims",
                 "rev", "bitcast_convert_type", "copy"}


def _jaxpr_liveness_peak(jaxpr) -> int:
    """Peak live intermediate bytes over a jaxpr's equation schedule
    (outvars live to the end; invars/constvars excluded — the caller
    accounts them as resident).  Layout ops (transpose/reshape/...)
    alias their input: they add no bytes, and extend the aliased
    value's liveness instead."""
    import jax

    eqns = jaxpr.eqns
    defined = set()
    for eqn in eqns:
        for v in eqn.outvars:
            defined.add(v)

    # alias classes: out -> canonical root (resolved transitively since
    # eqns are processed in def order)
    root: Dict[Any, Any] = {}
    for eqn in eqns:
        if eqn.primitive.name in _LAYOUT_PRIMS and len(eqn.outvars) == 1:
            srcs = [v for v in eqn.invars
                    if not isinstance(v, jax.core.Literal)]
            out = eqn.outvars[0]
            if len(srcs) == 1 and _aval_nbytes(
                    getattr(out, "aval", None)) == _aval_nbytes(
                    srcs[0].aval):
                root[out] = root.get(srcs[0], srcs[0])

    def canon(v):
        return root.get(v, v)

    last_use: Dict[Any, int] = {}
    for n, eqn in enumerate(eqns):
        for v in eqn.invars:
            if isinstance(v, jax.core.Literal):
                continue
            c = canon(v)
            if c in defined:
                last_use[c] = n
    for v in jaxpr.outvars:
        if not isinstance(v, jax.core.Literal):
            c = canon(v)
            if c in defined:
                last_use[c] = len(eqns)

    live: Dict[Any, int] = {}
    running = 0
    peak = 0
    for n, eqn in enumerate(eqns):
        inner = (_inner_jaxpr(eqn)
                 if eqn.primitive.name in _CALL_PRIMITIVES else None)
        transient = 0
        if inner is not None:
            # the inner peak excludes the inner invars (resident at the
            # outer level) but INCLUDES the inner outputs (live to the
            # inner end); the outer level counts this eqn's outvars
            # again below, so subtract exactly that overlap
            out_bytes = sum(_aval_nbytes(getattr(v, "aval", None))
                            for v in inner.outvars
                            if not isinstance(v, jax.core.Literal))
            transient = max(0, _jaxpr_liveness_peak(inner) - out_bytes)
        elif eqn.primitive.name == "scan":
            body = _inner_jaxpr(eqn)
            if body is not None:
                transient = _jaxpr_liveness_peak(body)
        elif eqn.primitive.name == "cond":
            branches = eqn.params.get("branches", ())
            transient = max((_jaxpr_liveness_peak(
                getattr(b, "jaxpr", b)) for b in branches), default=0)
        for v in eqn.outvars:
            c = canon(v)
            if c is not v:
                continue  # layout alias: no new allocation
            nb = _aval_nbytes(getattr(v, "aval", None))
            if last_use.get(c, -1) >= n:
                if c not in live:
                    live[c] = nb
                    running += nb
            else:
                transient += nb  # dead-on-arrival (DropVar) output
        peak = max(peak, running + transient)
        for v in [v for v, lu in last_use.items() if lu == n and v in live]:
            running -= live.pop(v)
    return peak


def estimate_jit_memory(fn, *sample_args,
                        arg_specs: Optional[Sequence] = None,
                        mesh=None, param_argnums: Sequence[int] = (),
                        kv_caches: Sequence[Tuple[tuple, Any]] = (),
                        static_argnums: Sequence[int] = (),
                        activation_shards: int = 1) -> MemoryEstimate:
    """Estimate per-device memory of a jittable callable on abstract
    inputs (``jax.ShapeDtypeStruct`` or concrete arrays; never executes).

    arg_specs: optional PartitionSpecs aligned with the FLATTENED leaves
    of sample_args (None = replicated); mesh supplies axis sizes.
    param_argnums: top-level argument positions counted as parameters in
    the breakdown (default: everything is ``inputs``).
    activation_shards: divisor for intermediate liveness when GSPMD
    shards the program's activations (e.g. the tp degree of a
    Megatron-sharded block, whose matmul intermediates are tp-sharded);
    the default 1 counts intermediates replicated — the safe upper
    bound for a fit check.
    """
    import jax

    closed = jax.make_jaxpr(
        fn, static_argnums=tuple(static_argnums))(*sample_args)
    jaxpr = closed.jaxpr
    est = MemoryEstimate()
    axis_sizes = _axis_sizes(mesh)

    # resident: flattened args + closed-over consts
    leaves_per_arg = [
        (i, jax.tree_util.tree_leaves(a)) for i, a in
        enumerate(sample_args) if i not in set(static_argnums)]
    flat: List[Tuple[int, Any]] = [(i, leaf) for i, ls in leaves_per_arg
                                   for leaf in ls]
    specs = list(arg_specs or [])
    for k, (argnum, leaf) in enumerate(flat):
        spec = specs[k] if k < len(specs) else None
        nbytes = _sharded_nbytes(tuple(leaf.shape), leaf.dtype, spec,
                                 axis_sizes)
        if argnum in set(param_argnums):
            est.param_bytes += nbytes
        else:
            est.input_bytes += nbytes
    for c in closed.consts:
        est.input_bytes += _aval_nbytes(
            jax.api_util.shaped_abstractify(c))

    for shape, dt in kv_caches:
        est.kv_cache_bytes += _sharded_nbytes(tuple(shape), dt, None,
                                              axis_sizes)

    est.activation_peak_bytes = _jaxpr_liveness_peak(jaxpr) // max(
        int(activation_shards), 1)
    est.output_bytes = sum(
        _aval_nbytes(getattr(v, "aval", None)) for v in jaxpr.outvars
        if not isinstance(v, jax.core.Literal))
    est.n_values = sum(len(e.outvars) for e in jaxpr.eqns)
    return est


def _flat_cache_pair(pair):
    """Flatten one layer's (k, v) cache entry to raw arrays — an int8
    cache leaf is a (payload, scales) pair (models.transformer), a float
    leaf one array."""
    out = []
    for leaf in pair:
        if isinstance(leaf, tuple):
            out.extend(part._data for part in leaf)
        else:
            out.append(leaf._data)
    return tuple(out)


def kv_cache_residency(block, batch: int, max_length: int,
                       dtype: str = "float32", cache_spec=None,
                       mesh=None) -> Tuple[int, List[Tuple[tuple, str]]]:
    """Per-device bytes (and the (shape, dtype) list) of a block's KV
    cache at ``(batch, max_length)`` under ``cache_spec`` — abstractly
    evaluated via ``jax.eval_shape``, no allocation."""
    import jax

    def _mk():
        return tuple(_flat_cache_pair(pair)
                     for pair in block.init_cache(batch, max_length,
                                                  dtype))

    try:
        leaves = jax.eval_shape(_mk)
    except Exception:
        leaves = _mk()  # tiny blocks: concrete fallback
    axis_sizes = _axis_sizes(mesh)
    shapes: List[Tuple[tuple, str]] = []
    total = 0
    for pair in leaves:
        for leaf in pair:
            # an int8 cache's (B, KV, T) scale tensors drop only the
            # trailing head-dim, so the payload spec prices them too
            # (_sharded_nbytes ignores spec axes past the leaf's ndim)
            shapes.append((tuple(leaf.shape), str(leaf.dtype)))
            total += _sharded_nbytes(tuple(leaf.shape), leaf.dtype,
                                     cache_spec, axis_sizes)
    return total, shapes


def paged_kv_cache_residency(block, num_blocks: int, block_size: int,
                             dtype: str = "float32", cache_spec=None,
                             mesh=None, blocks_in_use: Optional[int] = None,
                             shared_extra_refs: int = 0,
                             pinned_blocks: int = 0,
                             spilled_blocks: int = 0,
                             engine=None) -> Dict[str, Any]:
    """Per-device byte accounting of a BLOCK-PAGED KV cache
    (:class:`~mxtpu.parallel.PagedContinuousBatchingEngine`):
    abstractly evaluated like :func:`kv_cache_residency`, plus the
    paged split the slot layout cannot express —

    - ``bytes_per_block``: per-device bytes one page costs across every
      layer's (k, v) pools (the granularity admission allocates at);
    - ``resident_bytes`` / ``free_bytes``: the pool split at
      ``blocks_in_use`` allocated pages (the +1 null page is counted in
      ``total_bytes`` — it is real HBM — but never in the free pool);
    - ``shared_savings_bytes``: ``shared_extra_refs`` — the sum of
      (refcount − 1) over shared pages — times ``bytes_per_block``:
      what an unshared layout would ADDITIONALLY hold resident right
      now.  Refcounted pages are deliberately priced ONCE in
      ``resident_bytes`` — a page shared by N requests is one page.
    - the HIERARCHICAL tiers (docs/inference.md), priced SEPARATELY:
      ``pinned_bytes`` = ``pinned_blocks`` × bytes_per_block is the
      slice of ``resident_bytes`` the cache is holding past its last
      table reference — it counts against the HBM budget like any
      resident page; ``spilled_bytes_host`` = ``spilled_blocks`` ×
      ``bytes_per_block_host`` prices the host-RAM tier at UNSHARDED
      page bytes (host copies are full replicated pages) and belongs
      to a HOST budget, never the HBM one (:func:`check_memory`'s
      ``host_budget_bytes``).

    Pass a live engine (``engine=``) to read ``num_blocks`` /
    ``block_size`` / occupancy / sharing / tier counters — and the
    pool's actual cache dtype, sharding spec and mesh — from it
    instead of spelling them out."""
    import jax

    if engine is not None:
        st = engine.stats
        num_blocks = st["num_blocks"]
        block_size = st["block_size"]
        blocks_in_use = st["blocks_in_use"]
        shared_extra_refs = st["shared_extra_refs"]
        pinned_blocks = st.get("pinned_blocks", 0)
        spilled_blocks = st.get("spilled_blocks", 0)
        dtype = engine._cache_dtype
        cache_spec = engine._dec._cache_spec
        mesh = engine._mesh

    def _mk():
        return tuple(_flat_cache_pair(pair)
                     for pair in block.init_block_pool(
                         num_blocks + 1, block_size, dtype))

    try:
        leaves = jax.eval_shape(_mk)
    except Exception:
        leaves = _mk()  # tiny blocks: concrete fallback
    axis_sizes = _axis_sizes(mesh)
    shapes: List[Tuple[tuple, str]] = []
    total = 0
    per_block = 0
    per_block_host = 0
    for pair in leaves:
        for leaf in pair:
            # int8 pools carry (N, KV, bs) scale tensors page-aligned
            # beside their payload pages: same axis-0 page granularity,
            # same spec truncation as kv_cache_residency — so
            # bytes_per_block prices a page's payload PLUS its scales
            shapes.append((tuple(leaf.shape), str(leaf.dtype)))
            nbytes = _sharded_nbytes(tuple(leaf.shape), leaf.dtype,
                                     cache_spec, axis_sizes)
            total += nbytes
            per_block += nbytes // leaf.shape[0]
            # host copies are unsharded full pages (the swap program
            # replicates its read)
            per_block_host += _sharded_nbytes(
                tuple(leaf.shape), leaf.dtype, None,
                axis_sizes) // leaf.shape[0]
    out = {
        "total_bytes": total,
        "bytes_per_block": per_block,
        "bytes_per_block_host": per_block_host,
        "num_blocks": int(num_blocks),
        "block_size": int(block_size),
        "shapes": shapes,
    }
    if blocks_in_use is not None:
        out["blocks_in_use"] = int(blocks_in_use)
        out["resident_bytes"] = int(blocks_in_use) * per_block
        out["free_bytes"] = (int(num_blocks)
                             - int(blocks_in_use)) * per_block
    out["shared_extra_refs"] = int(shared_extra_refs)
    out["shared_savings_bytes"] = int(shared_extra_refs) * per_block
    out["pinned_blocks"] = int(pinned_blocks)
    out["pinned_bytes"] = int(pinned_blocks) * per_block
    out["spilled_blocks"] = int(spilled_blocks)
    out["spilled_bytes_host"] = int(spilled_blocks) * per_block_host
    return out


# -- the XLA cross-check --------------------------------------------------

def xla_memory_stats(fn, *sample_args, in_shardings=None,
                     out_shardings=None, donate_argnums=(),
                     static_argnums=()) -> Dict[str, int]:
    """Ground truth: compile ``fn`` (abstract — no execution) and return
    ``compile().memory_analysis()`` totals.  ``total`` sums argument +
    output + temp + alias bytes, the figure :class:`MemoryEstimate`
    ``total_bytes`` models (tests assert agreement within tolerance on
    the CPU reference graphs)."""
    import jax

    kw = {}
    if in_shardings is not None:
        kw["in_shardings"] = in_shardings
    if out_shardings is not None:
        kw["out_shardings"] = out_shardings
    jitted = jax.jit(fn, donate_argnums=tuple(donate_argnums),
                     static_argnums=tuple(static_argnums), **kw)
    ma = jitted.lower(*sample_args).compile().memory_analysis()
    out = {"argument": int(ma.argument_size_in_bytes),
           "output": int(ma.output_size_in_bytes),
           "temp": int(ma.temp_size_in_bytes),
           "alias": int(ma.alias_size_in_bytes)}
    out["total"] = sum(out.values())
    return out


# -- the VMEM model (Pallas kernel calls) ---------------------------------
# The HBM model above answers "does the program fit per device"; this
# answers the on-chip sibling: "does ONE GRID STEP of a Pallas kernel fit
# in VMEM".  mxtpu.analysis.kernel_check turns the estimate into its
# K003/M007 diagnostics; the descriptors it consumes are duck-typed (any
# object with .operands/.scratch/.prefetch of the KernelSpec shape).

#: TPU lane width: the last dim of every VMEM tile is 128 wide.
LANE = 128

# minimum sublane tile (second-to-last dim) per element width: fp32/int32
# tile (8, 128), bf16/fp16 (16, 128), int8/fp8 (32, 128)
_SUBLANE_BY_ITEMSIZE = {1: 32, 2: 16, 4: 8}


def sublane_tile(dtype) -> int:
    """Minimum second-to-last tile dim for ``dtype`` on TPU (8 fp32,
    16 bf16, 32 int8 — the dtype packing rule kernel_check's K002
    enforces)."""
    return _SUBLANE_BY_ITEMSIZE.get(_itemsize(dtype), 8)


def _tile_padded_bytes(shape, dtype) -> int:
    """Bytes one block/scratch buffer occupies in VMEM: the last dim
    pads to the 128-lane tile, the second-to-last to the dtype's sublane
    tile (partial tiles are allocated whole); leading dims multiply."""
    shape = tuple(int(d) for d in shape)
    if not shape:
        return _itemsize(dtype)
    dims = list(shape)
    dims[-1] = math.ceil(dims[-1] / LANE) * LANE
    if len(dims) >= 2:
        sub = sublane_tile(dtype)
        dims[-2] = math.ceil(dims[-2] / sub) * sub
    n = _itemsize(dtype)
    for d in dims:
        n *= d
    return n


def kernel_vmem_estimate(spec, buffering: int = 2) -> Dict[str, Any]:
    """Per-grid-step VMEM bytes of one Pallas kernel call described by a
    :class:`~mxtpu.analysis.kernel_check.KernelSpec`.

    The model: every in/out block is resident tile-padded and — because
    the Pallas TPU pipeline double-buffers blocks across grid steps —
    counted ``buffering`` times (default 2; pass 1 for the unpipelined
    lower bound); scratch buffers are single-resident; scalar-prefetch
    operands live in SMEM and are priced separately
    (``smem_prefetch_bytes``), never against the VMEM total.

    Returns a dict: ``in_bytes`` / ``out_bytes`` (single-copy block
    sums), ``scratch_bytes``, ``smem_prefetch_bytes``, ``buffering``,
    ``total_bytes`` = buffering × (in + out) + scratch, and
    ``per_operand`` — (name, kind, block_shape, dtype, padded bytes)
    tuples for the breakdown diagnostics.
    """
    in_bytes = 0
    out_bytes = 0
    per_operand = []
    for op in spec.operands:
        nbytes = _tile_padded_bytes(op.block_shape, op.dtype)
        if op.kind == "out":
            out_bytes += nbytes
        else:
            in_bytes += nbytes
        per_operand.append((op.name, op.kind, tuple(op.block_shape),
                            str(op.dtype), nbytes))
    scratch_bytes = 0
    for sc in spec.scratch:
        nbytes = _tile_padded_bytes(sc.shape, sc.dtype)
        scratch_bytes += nbytes
        per_operand.append((sc.name, "scratch", tuple(sc.shape),
                            str(sc.dtype), nbytes))
    import numpy as np

    smem = 0
    for pf in spec.prefetch:
        vals = np.asarray(pf.values)
        smem += int(vals.size) * _itemsize(vals.dtype)
    buffering = max(int(buffering), 1)
    return {
        "in_bytes": in_bytes,
        "out_bytes": out_bytes,
        "scratch_bytes": scratch_bytes,
        "smem_prefetch_bytes": smem,
        "buffering": buffering,
        "total_bytes": buffering * (in_bytes + out_bytes) + scratch_bytes,
        "per_operand": per_operand,
    }


def kernel_hbm_traffic(spec, workload=None) -> Dict[str, Any]:
    """Deterministic per-invocation HBM traffic of one Pallas kernel
    call described by a :class:`~mxtpu.analysis.kernel_check.KernelSpec`
    — the DMA-count sibling of :func:`kernel_vmem_estimate` (which
    answers residency, not traffic).

    The model mirrors the Pallas TPU pipeline: one block DMA per grid
    step per operand, ELIDED when the operand's index map returns the
    same block index as the previous step (the pipeline skips the copy
    for an unchanged window — this is what makes the paged kernels'
    null-page-0 routing a no-op read: every padded step lands on the
    same page).  Each operand's index map is evaluated over the FULL
    grid in execution order (last axis innermost) with the spec's
    scalar-prefetch values, so ragged block-table walks are priced
    against the real tables: the decode kernel's pool traffic comes out
    O(valid pages), not O(table width), and the claim is a numeric
    assertion, not prose.

    ``workload``: optional dict — ``max_grid_points`` (default 1<<22)
    caps the sweep; a grid past the cap raises instead of sampling,
    because a *deterministic* cost model must not silently verdict a
    partial walk.

    Returns per-operand ``fetches`` (elided-DMA count), ``unique_blocks``
    (distinct windows touched), ``block_bytes`` (payload bytes, not
    tile-padded — traffic counts bytes moved, not VMEM allocated) and
    ``bytes``; plus ``in_bytes`` / ``out_bytes`` / ``total_bytes`` and
    ``grid_points``.
    """
    import numpy as np

    workload = dict(workload or {})
    cap = int(workload.get("max_grid_points", 1 << 22))
    grid = tuple(max(int(g), 1) for g in spec.grid)
    total = 1
    for g in grid:
        total *= g
    if total > cap:
        raise ValueError(
            "kernel_hbm_traffic: grid %r has %d points, past the %d "
            "cap — this model sweeps the FULL grid (deterministic "
            "traffic, no sampling); raise workload['max_grid_points']"
            % (grid, total, cap))

    # lazy import: kernel_check imports this module at load time
    from .kernel_check import _as_index_arrays, _prefetch_values

    axes = [np.arange(g) for g in grid]
    mesh = np.meshgrid(*axes, indexing="ij") if axes else []
    coords = [m.reshape(-1) for m in mesh]
    npoints = len(coords[0]) if coords else 1
    pf_vals = _prefetch_values(spec)

    per_operand: Dict[str, Dict[str, Any]] = {}
    in_bytes = 0
    out_bytes = 0
    for op in spec.operands:
        block_bytes = _itemsize(op.dtype)
        for d in op.block_shape:
            block_bytes *= int(d)
        if op.index_map is None:
            fetches = unique = 1
        else:
            idx = _as_index_arrays(
                op.index_map(*coords, *pf_vals), len(op.block_shape),
                npoints)
            stack = np.stack(idx, axis=1)        # (npoints, ndim)
            changes = int(np.any(stack[1:] != stack[:-1],
                                 axis=1).sum()) if npoints > 1 else 0
            fetches = changes + 1
            unique = int(len(np.unique(stack, axis=0)))
        nbytes = fetches * block_bytes
        per_operand[op.name] = {
            "kind": op.kind, "fetches": fetches,
            "unique_blocks": unique, "block_bytes": block_bytes,
            "bytes": nbytes}
        if op.kind == "out":
            out_bytes += nbytes
        else:
            in_bytes += nbytes
    return {
        "per_operand": per_operand,
        "in_bytes": in_bytes,
        "out_bytes": out_bytes,
        "total_bytes": in_bytes + out_bytes,
        "grid_points": npoints,
    }


# -- the registered pass --------------------------------------------------

def check_memory(target, budget_bytes=None, known_shapes=None, rules=None,
                 mesh=None, kv_caches=(), sample_args=None,
                 headroom: float = 0.9, top_k: int = 3,
                 host_budget_bytes=None, host_kv_bytes: int = 0,
                 **shape_kwargs) -> Report:
    """Budget check over a Symbol graph (or a jittable callable when
    ``sample_args`` is given); returns a Report of M0xx diagnostics.

    budget_bytes: int or a string like ``"16GiB"``; None checks nothing
    but still reports the M003 breakdown.

    The hierarchical cache's tiers are priced SEPARATELY
    (docs/inference.md "Hierarchical prefix cache"): pinned pages are
    part of the device pool — whatever ``kv_caches`` shapes carry them
    already counts against ``budget_bytes`` — while spilled chains
    live in HOST RAM and must not inflate the HBM verdict.  Pass their
    bytes (``paged_kv_cache_residency(...)["spilled_bytes_host"]``) as
    ``host_kv_bytes`` with a ``host_budget_bytes`` cap to get an M006
    ERROR when the host tier outgrows its budget."""
    report = Report()
    if callable(target) and not hasattr(target, "_topo"):
        if sample_args is None:
            raise ValueError(
                "check_memory on a callable needs sample_args "
                "(ShapeDtypeStructs or arrays)")
        est = estimate_jit_memory(target, *sample_args, mesh=mesh,
                                  kv_caches=kv_caches)
        subject = getattr(target, "__name__", repr(target))
    else:
        est = estimate_graph_memory(target, known_shapes=known_shapes,
                                    rules=rules, mesh=mesh,
                                    kv_caches=kv_caches, **shape_kwargs)
        subject = getattr(target, "name", "graph")

    bd = est.breakdown()
    # host tier reported beside the device breakdown but NEVER summed
    # into it — spilled chains are host RAM, not HBM
    bd3 = dict(bd, host_kv_cache=int(host_kv_bytes)) if host_kv_bytes \
        else bd
    report.add(Diagnostic(
        _PASS, "M003", Severity.INFO, subject,
        "per-device estimate: %s" % ", ".join(
            "%s=%s" % (k, format_bytes(v)) for k, v in bd3.items()),
        details=bd3))
    for name, nbytes in est.contributors[:top_k]:
        report.add(Diagnostic(
            _PASS, "M004", Severity.INFO, name,
            "largest liveness contributor at the activation peak: "
            "%s = %s" % (name, format_bytes(nbytes)),
            details={"bytes": nbytes}))
    if est.unknown_nodes:
        report.add(Diagnostic(
            _PASS, "M005", Severity.WARNING,
            est.unknown_nodes[0],
            "%d node(s) have unknown shapes (%s%s) — the estimate is a "
            "LOWER bound; provide input shapes" % (
                len(est.unknown_nodes),
                ", ".join(est.unknown_nodes[:5]),
                ", …" if len(est.unknown_nodes) > 5 else ""),
            details={"nodes": est.unknown_nodes[:32]}))
    if budget_bytes is not None:
        budget = parse_bytes(budget_bytes)
        total = est.total_bytes
        if total > budget:
            report.add(Diagnostic(
                _PASS, "M001", Severity.ERROR, subject,
                "estimated per-device memory %s exceeds the %s budget "
                "by %s (%s)" % (
                    format_bytes(total), format_bytes(budget),
                    format_bytes(total - budget),
                    ", ".join("%s=%s" % (k, format_bytes(v))
                              for k, v in bd.items()
                              if k != "total" and v)),
                details=bd))
        elif total > headroom * budget:
            report.add(Diagnostic(
                _PASS, "M002", Severity.WARNING, subject,
                "estimated per-device memory %s is within the %s budget "
                "but above %d%% headroom — one growth step from OOM" % (
                    format_bytes(total), format_bytes(budget),
                    int(headroom * 100)),
                details=bd))
    if host_budget_bytes is not None:
        host_budget = parse_bytes(host_budget_bytes)
        if int(host_kv_bytes) > host_budget:
            report.add(Diagnostic(
                _PASS, "M006", Severity.ERROR, subject,
                "host-RAM KV tier %s exceeds the %s host budget by %s "
                "— shrink host_cache_bytes or let the LRU evict "
                "(spilled chains are host memory, priced separately "
                "from the HBM budget)" % (
                    format_bytes(int(host_kv_bytes)),
                    format_bytes(host_budget),
                    format_bytes(int(host_kv_bytes) - host_budget)),
                details={"host_kv_bytes": int(host_kv_bytes),
                         "host_budget_bytes": host_budget}))
    return report


register_pass(_PASS)(check_memory)
