"""CLI for the analysis passes: ``python -m mxtpu.analysis``.

Subcommands:

- ``registry``           audit the full op registry (+ fault-site
  coverage of the tests/ tree when one is found)
- ``lint [PATH ...]``    trace-safety lint (default: the mxtpu package)
- ``graph FILE.json``    verify a saved symbol.json (``--shape name=2,3``
  repeatable for input shapes)
- ``memory FILE.json``   per-device HBM estimate of a saved symbol.json
  (``--shape`` as above, ``--budget 16GiB`` to fail over budget)
- ``compile [LEDGER.json]`` compile-discipline check: analyze a ledger
  dump written via ``MXTPU_COMPILE_LEDGER_DUMP``, or (no argument) run
  the in-process probe workload and check the live ledger
- ``donate``             donation/aliasing self-check: builds a tiny
  SPMDTrainer step and verifies its donated buffers alias
- ``kernel``             Pallas kernel-geometry check: the shipped
  kernels' KernelSpecs at their real TPU serving/training geometries
  (``--vmem-budget 16MiB`` to price a different ceiling)
- ``sharding``           sharding-rule self-check on a reference rule set
- ``obs``                observability coverage check: every declared
  fault site resolves to a registered trace event type and every
  compile-ledger site to a unified-metrics key (O001 on any loss)
- ``lifecycle``          serving-lifecycle sanitizer: release-path lint
  over both engines + the serving package (V006), ReplicaTransport
  conformance and a bounded model-check of the gateway/supervisor/
  router stack (V007/V008), and an armed page-sanitizer self-drive
  (V001–V005)
- ``all``                EVERY registered pass, each through its
  self-application probe (the repo self-lint; default).  A pass
  registered without a probe wired here gets a P001 ERROR — the gate
  cannot silently skip a new pass.

Exit status is 1 when diagnostics at or above ``--fail-on`` (default
``error``) were produced, so the command slots into CI directly.
"""

from __future__ import annotations

import argparse
import sys

from . import (Report, Severity, audit_registry, check_compiles,
               check_kernels, check_memory, check_observability,
               check_sharding, list_passes, trace_lint, verify_graph)
from .diagnostics import Diagnostic


def _parse_shape_args(pairs):
    shapes = {}
    for p in pairs or ():
        if "=" not in p:
            raise SystemExit(f"--shape expects name=d0,d1,...  got {p!r}")
        name, dims = p.split("=", 1)
        shapes[name] = tuple(int(d) for d in dims.split(",") if d != "")
    return shapes


def _self_apply_registry(include_unverified: bool = False) -> Report:
    import mxtpu.ndarray  # noqa: F401 — populate the registry
    return audit_registry(include_unverified=include_unverified)


def _self_apply_lint(paths=None) -> Report:
    return trace_lint(paths or None)


def _self_apply_compile() -> Report:
    """Populate the live ledger with a small, correctly-disciplined
    workload (bulked eager segments re-flushed for cache hits) and run
    the discipline checker over everything this process recorded."""
    import numpy as np

    import mxtpu as mx
    from mxtpu import engine

    x = mx.nd.array(np.arange(8.0, dtype=np.float32))
    for _ in range(2):
        with engine.bulk(8):
            ((x * 2.0) + 1.0).asnumpy()  # trace-ok: analysis probe
    return check_compiles()


def _reference_graph():
    """The reference MLP the graph/memory passes self-check with."""
    from .. import symbol as sym

    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=128, name="selfcheck_fc1")
    act = sym.Activation(fc1, act_type="relu", name="selfcheck_act")
    return sym.FullyConnected(act, num_hidden=10, name="selfcheck_fc2")


def _self_apply_graph() -> Report:
    """Structural + shape/dtype verification of the reference MLP."""
    return verify_graph(_reference_graph(), data=(32, 64))


def _self_apply_memory() -> Report:
    """Estimate the reference MLP graph against a generous per-device
    budget."""
    return check_memory(_reference_graph(), budget_bytes="1GiB",
                        data=(32, 64))


def _self_apply_sharding() -> Report:
    """Validate a reference Megatron column→row rule pair against
    matching params on a {dp, tp} mesh."""
    from ..parallel.sharding import PartitionSpec, ShardingRules

    rules = ShardingRules([
        (r"\.q_proj\.weight$", PartitionSpec("tp", None)),
        (r"\.out_proj\.weight$", PartitionSpec(None, "tp")),
        (r"\.bias$", PartitionSpec(None)),
    ])
    params = {"layers.0.attn.q_proj.weight": (64, 64),
              "layers.0.attn.out_proj.weight": (64, 64),
              "layers.0.attn.q_proj.bias": (64,)}
    return check_sharding(rules, params, {"dp": 2, "tp": 4})


def _self_apply_donation() -> Report:
    """Build a tiny SPMDTrainer (donate=True, the default) and verify
    its compiled step's donated buffers actually alias."""
    import numpy as np

    import mxtpu as mx
    from ..gluon import loss as gloss, nn
    from ..parallel.mesh import DeviceMesh
    from ..parallel.trainer import SPMDTrainer
    from .donation_check import check_trainer_donation

    mx.random.seed(0)
    net = nn.Dense(8, in_units=4)
    net.initialize()
    trainer = SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                          DeviceMesh(dp=1),
                          optimizer_params={"learning_rate": 0.1,
                                            "momentum": 0.9})
    X = mx.nd.array(np.zeros((4, 4), np.float32))
    y = mx.nd.array(np.zeros((4,), np.float32))
    # lowering-level verification (the aliasing attributes): the
    # executable-level confirmation is exercised by the test suite
    return check_trainer_donation(trainer, X, y, compile=False)


def _self_apply_kernels(vmem_budget=None) -> Report:
    """Verdict the shipped Pallas kernels' call geometry at their real
    TPU serving/training geometries (fp32 + int8, decode + W-wide
    verify) — the ROADMAP-item-2 merge gate."""
    kw = {}
    if vmem_budget is not None:
        kw["vmem_budget"] = vmem_budget
    return check_kernels(**kw)


def _self_apply_obs() -> Report:
    """Observability coverage over the live process state: every
    declared fault site resolves to a trace event type, every ledger
    site to a unified-metrics key (O001 on any loss)."""
    return check_observability(include_summary=True)


def _self_apply_lifecycle() -> Report:
    """Serving-lifecycle sanitizer self-application: release-path lint
    over the in-repo engines (V006), transport conformance + bounded
    model check of the real service stack (V007/V008), and the armed
    page-sanitizer self-drive (V001–V005).  All host-side."""
    from .lifecycle_check import lifecycle_check
    return lifecycle_check()


# Every registered pass needs a self-application probe here; `all` runs
# each one and emits a P001 ERROR for any pass left unwired, so a new
# pass cannot be silently skipped by the CI gate.
_SELF_APPLY = {
    "audit_registry": _self_apply_registry,
    "trace_lint": _self_apply_lint,
    "compile_check": _self_apply_compile,
    "verify_graph": _self_apply_graph,
    "memory_estimate": _self_apply_memory,
    "check_sharding": _self_apply_sharding,
    "donation_check": _self_apply_donation,
    "kernel_check": _self_apply_kernels,
    "obs_check": _self_apply_obs,
    "lifecycle_check": _self_apply_lifecycle,
}


def _self_apply_all(lint_paths=None, include_unverified: bool = False,
                    vmem_budget=None) -> Report:
    """Every registered pass through its probe; the lint/registry/
    kernel flags `all` accepts are forwarded to the matching probes."""
    forwarded = {
        "audit_registry": dict(include_unverified=include_unverified),
        "trace_lint": dict(paths=lint_paths),
        "kernel_check": (dict(vmem_budget=vmem_budget)
                         if vmem_budget is not None else {}),
    }
    report = Report()
    for name in list_passes():
        probe = _SELF_APPLY.get(name)
        if probe is None:
            report.add(Diagnostic(
                "analysis_cli", "P001", Severity.ERROR, name,
                "registered analysis pass %r has no self-application "
                "probe wired into `python -m mxtpu.analysis all` — the "
                "CI gate would silently skip it; add a probe to "
                "_SELF_APPLY in mxtpu/analysis/__main__.py" % name))
            continue
        report.extend(probe(**forwarded.get(name, {})))
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxtpu.analysis",
        description="static graph verifier, sharding checker, registry "
                    "audit, trace-safety lint, compile-discipline "
                    "checker, HBM estimator, donation checker, and "
                    "Pallas kernel-geometry checker")
    ap.add_argument("command", nargs="?", default="all",
                    choices=["all", "registry", "lint", "graph",
                             "memory", "compile", "donate", "kernel",
                             "sharding", "obs", "lifecycle"])
    ap.add_argument("paths", nargs="*",
                    help="lint: files/dirs; graph/memory: one "
                         "symbol.json; compile: one ledger dump")
    ap.add_argument("--shape", action="append", metavar="NAME=D0,D1",
                    help="input shape hint for `graph`/`memory` "
                         "(repeatable)")
    ap.add_argument("--budget", default=None, metavar="BYTES",
                    help="memory: per-device budget (e.g. 16GiB); "
                         "over-budget estimates are errors")
    ap.add_argument("--vmem-budget", default=None, metavar="BYTES",
                    help="kernel: per-grid-step VMEM budget "
                         "(default 16MiB)")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as JSON")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "info"],
                    help="exit non-zero at this severity or above")
    ap.add_argument("--include-unverified", action="store_true",
                    help="registry: report R004 for unverifiable ops")
    args = ap.parse_args(argv)

    report = Report()
    if args.command == "all":
        report.extend(_self_apply_all(
            lint_paths=args.paths or None,
            include_unverified=args.include_unverified,
            vmem_budget=args.vmem_budget))
    if args.command == "registry":
        report.extend(_self_apply_registry(
            include_unverified=args.include_unverified))
    if args.command == "lint":
        report.extend(_self_apply_lint(args.paths))
    if args.command == "graph":
        if len(args.paths) != 1:
            raise SystemExit("graph: exactly one symbol.json path")
        from ..symbol import load
        sym = load(args.paths[0])
        report.extend(verify_graph(
            sym, known_shapes=_parse_shape_args(args.shape)))
    if args.command == "memory":
        if len(args.paths) != 1:
            raise SystemExit("memory: exactly one symbol.json path")
        from ..symbol import load
        sym = load(args.paths[0])
        report.extend(check_memory(
            sym, budget_bytes=args.budget,
            known_shapes=_parse_shape_args(args.shape)))
    if args.command == "compile":
        if args.paths:
            from .compile_ledger import CompileLedger
            with open(args.paths[0]) as f:
                ledger = CompileLedger.from_json(f.read())
            report.extend(check_compiles(ledger, include_summary=True))
        else:
            report.extend(_self_apply_compile())
    if args.command == "donate":
        report.extend(_self_apply_donation())
    if args.command == "kernel":
        report.extend(_self_apply_kernels(vmem_budget=args.vmem_budget))
    if args.command == "sharding":
        report.extend(_self_apply_sharding())
    if args.command == "obs":
        report.extend(_self_apply_obs())
    if args.command == "lifecycle":
        report.extend(_self_apply_lifecycle())

    if args.json:
        print(report.to_json())
    else:
        print(report)

    threshold = Severity[args.fail_on.upper()]
    failing = report.filter(min_severity=threshold)
    return 1 if len(failing) else 0


if __name__ == "__main__":
    sys.exit(main())
