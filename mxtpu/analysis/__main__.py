"""CLI for the analysis passes: ``python -m mxtpu.analysis``.

Subcommands:

- ``registry``           audit the full op registry
- ``lint [PATH ...]``    trace-safety lint (default: the mxtpu package)
- ``graph FILE.json``    verify a saved symbol.json (``--shape name=2,3``
  repeatable for input shapes)
- ``memory FILE.json``   per-device HBM estimate of a saved symbol.json
  (``--shape`` as above, ``--budget 16GiB`` to fail over budget)
- ``compile [LEDGER.json]`` compile-discipline check: analyze a ledger
  dump written via ``MXTPU_COMPILE_LEDGER_DUMP``, or (no argument) run
  the in-process probe workload and check the live ledger
- ``donate``             donation/aliasing self-check: builds a tiny
  SPMDTrainer step and verifies its donated buffers alias
- ``all``                registry + lint + the compile/memory/donation
  self-applications (the repo self-lint; default)

Exit status is 1 when diagnostics at or above ``--fail-on`` (default
``error``) were produced, so the command slots into CI directly.
"""

from __future__ import annotations

import argparse
import sys

from . import (Report, Severity, audit_registry, check_compiles,
               check_memory, trace_lint, verify_graph)


def _parse_shape_args(pairs):
    shapes = {}
    for p in pairs or ():
        if "=" not in p:
            raise SystemExit(f"--shape expects name=d0,d1,...  got {p!r}")
        name, dims = p.split("=", 1)
        shapes[name] = tuple(int(d) for d in dims.split(",") if d != "")
    return shapes


def _self_apply_compile() -> Report:
    """Populate the live ledger with a small, correctly-disciplined
    workload (bulked eager segments re-flushed for cache hits) and run
    the discipline checker over everything this process recorded."""
    import numpy as np

    import mxtpu as mx
    from mxtpu import engine

    x = mx.nd.array(np.arange(8.0, dtype=np.float32))
    for _ in range(2):
        with engine.bulk(8):
            ((x * 2.0) + 1.0).asnumpy()  # trace-ok: analysis probe
    return check_compiles()


def _self_apply_memory() -> Report:
    """Estimate the reference MLP graph (the same one the graph verifier
    self-checks with) against a generous per-device budget."""
    from .. import symbol as sym

    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=128, name="selfcheck_fc1")
    act = sym.Activation(fc1, act_type="relu", name="selfcheck_act")
    net = sym.FullyConnected(act, num_hidden=10, name="selfcheck_fc2")
    return check_memory(net, budget_bytes="1GiB", data=(32, 64))


def _self_apply_donation() -> Report:
    """Build a tiny SPMDTrainer (donate=True, the default) and verify
    its compiled step's donated buffers actually alias."""
    import numpy as np

    import mxtpu as mx
    from ..gluon import loss as gloss, nn
    from ..parallel.mesh import DeviceMesh
    from ..parallel.trainer import SPMDTrainer
    from .donation_check import check_trainer_donation

    mx.random.seed(0)
    net = nn.Dense(8, in_units=4)
    net.initialize()
    trainer = SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                          DeviceMesh(dp=1),
                          optimizer_params={"learning_rate": 0.1,
                                            "momentum": 0.9})
    X = mx.nd.array(np.zeros((4, 4), np.float32))
    y = mx.nd.array(np.zeros((4,), np.float32))
    # lowering-level verification (the aliasing attributes): the
    # executable-level confirmation is exercised by the test suite
    return check_trainer_donation(trainer, X, y, compile=False)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxtpu.analysis",
        description="static graph verifier, sharding checker, registry "
                    "audit, trace-safety lint, compile-discipline "
                    "checker, HBM estimator, and donation checker")
    ap.add_argument("command", nargs="?", default="all",
                    choices=["all", "registry", "lint", "graph",
                             "memory", "compile", "donate"])
    ap.add_argument("paths", nargs="*",
                    help="lint: files/dirs; graph/memory: one "
                         "symbol.json; compile: one ledger dump")
    ap.add_argument("--shape", action="append", metavar="NAME=D0,D1",
                    help="input shape hint for `graph`/`memory` "
                         "(repeatable)")
    ap.add_argument("--budget", default=None, metavar="BYTES",
                    help="memory: per-device budget (e.g. 16GiB); "
                         "over-budget estimates are errors")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as JSON")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "info"],
                    help="exit non-zero at this severity or above")
    ap.add_argument("--include-unverified", action="store_true",
                    help="registry: report R004 for unverifiable ops")
    args = ap.parse_args(argv)

    report = Report()
    if args.command in ("all", "registry"):
        import mxtpu.ndarray  # noqa: F401 — populate the registry
        report.extend(audit_registry(
            include_unverified=args.include_unverified))
    if args.command in ("all", "lint"):
        report.extend(trace_lint(args.paths or None))
    if args.command == "all":
        # self-apply the compile/memory/donation passes on built-in
        # probe workloads: the CI gate exercises every pass end to end
        report.extend(_self_apply_compile())
        report.extend(_self_apply_memory())
        report.extend(_self_apply_donation())
    if args.command == "graph":
        if len(args.paths) != 1:
            raise SystemExit("graph: exactly one symbol.json path")
        from ..symbol import load
        sym = load(args.paths[0])
        report.extend(verify_graph(
            sym, known_shapes=_parse_shape_args(args.shape)))
    if args.command == "memory":
        if len(args.paths) != 1:
            raise SystemExit("memory: exactly one symbol.json path")
        from ..symbol import load
        sym = load(args.paths[0])
        report.extend(check_memory(
            sym, budget_bytes=args.budget,
            known_shapes=_parse_shape_args(args.shape)))
    if args.command == "compile":
        if args.paths:
            from .compile_ledger import CompileLedger
            with open(args.paths[0]) as f:
                ledger = CompileLedger.from_json(f.read())
            report.extend(check_compiles(ledger, include_summary=True))
        else:
            report.extend(_self_apply_compile())
    if args.command == "donate":
        report.extend(_self_apply_donation())

    if args.json:
        print(report.to_json())
    else:
        print(report)

    threshold = Severity[args.fail_on.upper()]
    failing = report.filter(min_severity=threshold)
    return 1 if len(failing) else 0


if __name__ == "__main__":
    sys.exit(main())
