"""CLI for the analysis passes: ``python -m mxtpu.analysis``.

Subcommands:

- ``registry``           audit the full op registry
- ``lint [PATH ...]``    trace-safety lint (default: the mxtpu package)
- ``graph FILE.json``    verify a saved symbol.json (``--shape name=2,3``
  repeatable for input shapes)
- ``all``                registry + lint (the repo self-lint; default)

Exit status is 1 when diagnostics at or above ``--fail-on`` (default
``error``) were produced, so the command slots into CI directly.
"""

from __future__ import annotations

import argparse
import sys

from . import (Report, Severity, audit_registry, trace_lint, verify_graph)


def _parse_shape_args(pairs):
    shapes = {}
    for p in pairs or ():
        if "=" not in p:
            raise SystemExit(f"--shape expects name=d0,d1,...  got {p!r}")
        name, dims = p.split("=", 1)
        shapes[name] = tuple(int(d) for d in dims.split(",") if d != "")
    return shapes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxtpu.analysis",
        description="static graph verifier, sharding checker, registry "
                    "audit, and trace-safety lint")
    ap.add_argument("command", nargs="?", default="all",
                    choices=["all", "registry", "lint", "graph"])
    ap.add_argument("paths", nargs="*",
                    help="lint: files/dirs; graph: one symbol.json")
    ap.add_argument("--shape", action="append", metavar="NAME=D0,D1",
                    help="input shape hint for `graph` (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as JSON")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "info"],
                    help="exit non-zero at this severity or above")
    ap.add_argument("--include-unverified", action="store_true",
                    help="registry: report R004 for unverifiable ops")
    args = ap.parse_args(argv)

    report = Report()
    if args.command in ("all", "registry"):
        import mxtpu.ndarray  # noqa: F401 — populate the registry
        report.extend(audit_registry(
            include_unverified=args.include_unverified))
    if args.command in ("all", "lint"):
        report.extend(trace_lint(args.paths or None))
    if args.command == "graph":
        if len(args.paths) != 1:
            raise SystemExit("graph: exactly one symbol.json path")
        from ..symbol import load
        sym = load(args.paths[0])
        report.extend(verify_graph(
            sym, known_shapes=_parse_shape_args(args.shape)))

    if args.json:
        print(report.to_json())
    else:
        print(report)

    threshold = Severity[args.fail_on.upper()]
    failing = report.filter(min_severity=threshold)
    return 1 if len(failing) else 0


if __name__ == "__main__":
    sys.exit(main())
