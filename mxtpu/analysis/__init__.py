"""mxtpu.analysis — static analyses over the Symbol/CachedOp graph IR,
the op registry, sharding rules, and the compiled-program discipline
(parity: the nnvm graph-pass layer — InferShape/InferType/PlanMemory ran
as static analyses before execution; see PAPER.md §1 layer 6 and
src/executor/graph_executor.cc in the reference).

Ten shipped passes, each returning a :class:`Report` of located
:class:`Diagnostic` records instead of silent Nones or deep-in-XLA
failures:

- ``verify_graph(sym, **shapes)`` — duplicate node names, cycles,
  dangling arguments, full shape+dtype propagation with per-node error
  capture.
- ``check_sharding(rules, params, mesh)`` — PartitionSpec divisibility,
  axis reuse, unknown axes, dead/shadowed rules, estimated reshards.
- ``audit_registry()`` — num_outputs vs abstract eval, differentiable
  ops admit jax.vjp, alias-table integrity.
- ``trace_lint(paths)`` — AST lint for host-sync/retrace hazards in
  jit-traced code paths (plus dead ``# trace-ok`` suppressions).
- ``check_compiles()`` — turns the process-wide compile ledger (every
  jit entry point reports into it) into C0xx discipline diagnostics;
  ``compile_budget(n)`` asserts compile counts in tests.
- ``check_memory(target, budget)`` — sharding-aware per-device HBM
  estimate (params + activation-liveness peak + KV-cache residency)
  over Symbol graphs or jittable callables, M0xx against a budget.
- ``check_donation(fn, *args, donate_argnums=...)`` — verifies donated
  buffers actually alias in the compiled executable and flags missed
  donation opportunities (D0xx); ``check_trainer_donation`` applies it
  to an SPMDTrainer's compiled step.
- ``check_kernels(specs)`` — static TPU tile-geometry / VMEM-budget /
  grid-safety verdict over Pallas kernel call descriptors (K0xx),
  self-applied to the shipped ``ops/pallas`` kernels at their real
  serving/training geometries; ``kernel_vmem_estimate`` is the
  per-grid-step VMEM pricer beside the HBM model.
- ``check_observability()`` — observability coverage (O0xx): every
  declared fault site must resolve to a registered trace event type
  and every CompileLedger site to a unified-metrics key, so telemetry
  coverage is lost loudly (mirroring R005; docs/observability.md).
- ``lifecycle_check(paths)`` — serving-lifecycle sanitizer (V0xx): an
  opt-in shadow page-accounting state machine over BlockPool /
  HierarchicalCache (double-free, use-after-free, COW violations,
  pin leaks, host-tier orphans — V001–V005), an AST release-path lint
  proving every terminal path in both engines reaches the idempotent
  release helper (V006), and a small-scope model checker that
  exhaustively drives the gateway/supervisor/router stack over bounded
  configs and fault plans (V007/V008); ``page_sanitizing()`` arms the
  sanitizer per-scope, ``MXTPU_PAGE_SANITIZER=1`` process-wide.

CLI: ``python -m mxtpu.analysis`` (see docs/analysis.md).  Custom passes
register via :func:`register_pass` and run via :func:`run_pass`.
"""

from .compile_check import (CompileBudgetExceeded, check_compiles,
                            compile_budget)
from .compile_ledger import CompileLedger, Signature, get_ledger
from .diagnostics import (Diagnostic, Report, Severity, get_pass,
                          list_passes, register_pass, run_pass)
from .donation_check import check_donation, check_trainer_donation
from .graph_verify import verify_graph
from .kernel_check import (BlockOperand, KernelSpec, ScalarPrefetch,
                           ScratchOperand, check_kernels,
                           default_kernel_specs)
from .lifecycle_check import (PageLifecycleError, PageSanitizer,
                              check_protocol, conformance,
                              get_sanitizer, lifecycle_check,
                              page_sanitizing, release_path_lint)
from .memory_estimate import (MemoryEstimate, check_memory,
                              estimate_graph_memory, estimate_jit_memory,
                              kernel_hbm_traffic, kernel_vmem_estimate,
                              kv_cache_residency,
                              paged_kv_cache_residency, sublane_tile,
                              xla_memory_stats)
from .obs_check import check_observability
from .registry_audit import audit_fault_sites, audit_registry
from .sharding_check import check_sharding
from .trace_lint import lint_source, trace_lint

__all__ = [
    "Diagnostic", "Report", "Severity",
    "register_pass", "get_pass", "list_passes", "run_pass",
    "verify_graph", "check_sharding", "audit_registry",
    "audit_fault_sites", "trace_lint", "lint_source",
    "CompileLedger", "Signature", "get_ledger", "check_compiles",
    "compile_budget", "CompileBudgetExceeded",
    "MemoryEstimate", "check_memory", "estimate_graph_memory",
    "estimate_jit_memory", "kv_cache_residency",
    "paged_kv_cache_residency", "xla_memory_stats",
    "kernel_vmem_estimate", "kernel_hbm_traffic", "sublane_tile",
    "check_donation", "check_trainer_donation",
    "KernelSpec", "BlockOperand", "ScratchOperand", "ScalarPrefetch",
    "check_kernels", "default_kernel_specs",
    "check_observability",
    "PageLifecycleError", "PageSanitizer", "page_sanitizing",
    "get_sanitizer", "lifecycle_check", "release_path_lint",
    "check_protocol", "conformance",
]
