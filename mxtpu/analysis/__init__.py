"""mxtpu.analysis — static analyses over the Symbol/CachedOp graph IR,
the op registry, and sharding rules (parity: the nnvm graph-pass layer —
InferShape/InferType/PlanMemory ran as static analyses before execution;
see PAPER.md §1 layer 6 and src/executor/graph_executor.cc in the
reference).

Four shipped passes, each returning a :class:`Report` of located
:class:`Diagnostic` records instead of silent Nones or deep-in-XLA
failures:

- ``verify_graph(sym, **shapes)`` — duplicate node names, cycles,
  dangling arguments, full shape+dtype propagation with per-node error
  capture.
- ``check_sharding(rules, params, mesh)`` — PartitionSpec divisibility,
  axis reuse, unknown axes, dead/shadowed rules, estimated reshards.
- ``audit_registry()`` — num_outputs vs abstract eval, differentiable
  ops admit jax.vjp, alias-table integrity.
- ``trace_lint(paths)`` — AST lint for host-sync/retrace hazards in
  jit-traced code paths.

CLI: ``python -m mxtpu.analysis`` (see docs/analysis.md).  Custom passes
register via :func:`register_pass` and run via :func:`run_pass`.
"""

from .diagnostics import (Diagnostic, Report, Severity, get_pass,
                          list_passes, register_pass, run_pass)
from .graph_verify import verify_graph
from .registry_audit import audit_registry
from .sharding_check import check_sharding
from .trace_lint import lint_source, trace_lint

__all__ = [
    "Diagnostic", "Report", "Severity",
    "register_pass", "get_pass", "list_passes", "run_pass",
    "verify_graph", "check_sharding", "audit_registry", "trace_lint",
    "lint_source",
]
