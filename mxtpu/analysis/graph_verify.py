"""verify_graph: static structural + shape/dtype verification of a Symbol.

Parity: the reference ran nnvm's InferShape/InferType passes inside
GraphExecutor::Init and aborted with a per-node message ("Error in
operator fc1: ..."); our `Symbol.infer_shape` historically swallowed the
same failures into ``(None, None, None)``.  This pass walks the graph
once and reports everything it finds as located diagnostics:

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
G001        ERROR     duplicate node name (two distinct nodes share a name)
G002        ERROR     cycle through the named node (manual _Node wiring)
G003        WARNING   caller-provided shape for a name not in the graph
G004        INFO      graph input with no shape information
G005        ERROR     per-node shape/dtype inference failure (the exception
                      `_infer_shape_impl` used to swallow)
G006        WARNING   an output's shape could not be determined
==========  ========  =====================================================
"""

from __future__ import annotations

from typing import Optional

from .diagnostics import Diagnostic, Report, Severity, register_pass

__all__ = ["verify_graph"]

_PASS = "verify_graph"


def _walk_nodes(roots, report):
    """Iterative coloring DFS over _Node objects.

    Returns the list of reachable nodes; records a G002 diagnostic per
    back edge instead of looping forever (Symbol._topo's `seen` check
    happens to terminate on cycles but silently produces a broken
    order — a verifier must *name* the offending node)."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {}
    nodes = []
    index = {}

    for root in roots:
        if color.get(id(root), WHITE) == BLACK:
            continue
        # stack of (node, iterator-over-input-nodes)
        stack = [(root, iter([s._node for s in root.inputs]))]
        color[id(root)] = GRAY
        index[id(root)] = root
        while stack:
            node, it = stack[-1]
            child = next(it, None)
            if child is None:
                stack.pop()
                color[id(node)] = BLACK
                nodes.append(node)
                continue
            c = color.get(id(child), WHITE)
            if c == GRAY:
                report.add(Diagnostic(
                    _PASS, "G002", Severity.ERROR, child.name,
                    "cycle detected through node %r (op %s); the graph "
                    "is not a DAG — topological execution order is "
                    "undefined" % (child.name, child.op or "null")))
            elif c == WHITE:
                color[id(child)] = GRAY
                index[id(child)] = child
                stack.append((child,
                              iter([s._node for s in child.inputs])))
        # GRAY leftovers only exist if we aborted; loop always drains
    return nodes


def verify_graph(sym, known_shapes: Optional[dict] = None,
                 **shape_kwargs) -> Report:
    """Verify a Symbol graph; returns a Report of located diagnostics.

    known_shapes / **shape_kwargs: name → shape hints, same convention as
    ``sym.infer_shape`` (``__shape__`` attrs on variables are honored
    too).  Structural checks (duplicate names, cycles) run even when no
    shapes are given; propagation diagnostics need at least the data
    shapes to say anything useful.
    """
    report = Report()
    known = dict(known_shapes or {})
    known.update(shape_kwargs)

    nodes = _walk_nodes(sym._roots(), report)
    if not report.ok:
        # a cyclic graph has no meaningful topo order; shape propagation
        # (which uses Symbol._topo) would walk a broken order — stop here
        return report

    # G001: duplicate node names (distinct node objects sharing a name).
    # Composed graphs share the *same* node object across handles — that
    # is fine; two different nodes with one name break name-keyed
    # binding (`_execute` feeds both from one input_arrays slot).
    by_name = {}
    for n in nodes:
        by_name.setdefault(n.name, []).append(n)
    for name, group in sorted(by_name.items()):
        if len(group) > 1:
            kinds = ", ".join(g.op or "variable" for g in group)
            report.add(Diagnostic(
                _PASS, "G001", Severity.ERROR, name,
                "%d distinct nodes named %r (%s); name-keyed binding "
                "and arg lists will silently collide" %
                (len(group), name, kinds)))

    var_names = {n.name for n in nodes if n.op is None}

    # G003: caller supplied a shape for a name the graph does not have
    # (dangling/unused argument — the classic typo'd bind dict entry)
    for name in sorted(known):
        if name not in var_names:
            report.add(Diagnostic(
                _PASS, "G003", Severity.WARNING, name,
                "shape provided for %r but the graph has no such "
                "input; argument is unused" % name))

    # shape + dtype propagation with per-node error capture
    res = sym._propagate({k: v for k, v in known.items()
                          if k in var_names})

    for err in res.errors:
        report.add(Diagnostic(
            _PASS, "G005", Severity.ERROR, err.node,
            "shape/dtype inference failed at node %r (op %s): %s" %
            (err.node, err.op, err.error),
            details={"op": err.op, "error": err.error}))

    # G004: inputs that never got a shape (blocks downstream inference)
    for n in nodes:
        if n.op is None and res.var_shapes.get(n.name) is None:
            report.add(Diagnostic(
                _PASS, "G004", Severity.INFO, n.name,
                "input %r has no shape information (no __shape__ attr, "
                "not provided); downstream shapes stay unknown" % n.name))

    # G006: outputs whose shapes remain unknown despite no recorded error
    for node, idx in sym._output_entries():
        if res.shapes.get((id(node), idx)) is None:
            report.add(Diagnostic(
                _PASS, "G006", Severity.WARNING, node.name,
                "shape of output %d of node %r (op %s) could not be "
                "determined" % (idx, node.name, node.op or "null")))

    return report


register_pass(_PASS)(verify_graph)
