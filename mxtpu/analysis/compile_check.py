"""compile_check: turn the compile ledger into located C0xx diagnostics.

The discipline being checked is PERF.md round 5's: the number of
compiled programs per workload must be bounded by design (prefill
buckets + one pooled step + the pow2 speculative-verify window ladder
+ the hierarchical cache's ONE bounded swap-copy program for serving —
sites ``serving.slot_prefill`` / ``serving.step_slots`` /
``serving.verify_slots`` and their paged forms, plus ``serving.swap``;
one step program per batch signature for training —
``spmd_trainer.step``, and one fused window program per (N, shapes)
signature at ``spmd_trainer.step_multi``), never by traffic.  The ledger records every
jit-cache lookup with its signature pre-split into shapes / dtypes /
weak-type flags / static parts, so each growth mode gets its own code:

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
C001        ERROR     unbounded signature cardinality: ≥ threshold programs
                      at one site differing ONLY in shapes, with varying
                      dims that are not power-of-two bucketed — per-length
                      compiles that should bucket
C002        WARNING   weak-type / dtype drift: two compiles identical
                      except dtype or weak_type flags (the classic python-
                      scalar-vs-array retrace)
C003        WARNING   static-kwarg churn: ≥ threshold compiles with
                      identical shapes+dtypes differing only in the static
                      signature part
C004        INFO      bounded bucketed family: many shape-only signatures
                      whose varying dims are ALL powers of two (the
                      O(log T) growth the discipline allows) — advisory
C005        INFO      per-site summary (programs, hits/misses, top
                      cardinality); emitted with include_summary=True
==========  ========  =====================================================

``compile_budget(n)`` is the enforcement half: a context manager that
snapshots the ledger and raises :class:`CompileBudgetExceeded` when more
than ``n`` new programs were compiled inside the block, listing each
compile's site, signature, and call site.  Tier-1 tests use it to pin
the serving engine to (buckets + 1) programs so a bucketing regression
cannot land silently.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Tuple

from ..base import MXTPUError
from .compile_ledger import (CompileLedger, Miss, Signature, get_ledger)
from .diagnostics import Diagnostic, Report, Severity, register_pass

__all__ = ["check_compiles", "compile_budget", "CompileBudgetExceeded"]

_PASS = "compile_check"


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _shape_deltas(shapes_set) -> Tuple[Optional[List[int]], List[set]]:
    """Flattened positions where the shape tuples differ, and the value
    sets observed at each varying position.

    Returns ``(None, [])`` for structurally heterogeneous groups — a
    per-parameter optimizer site legitimately holds one signature per
    distinct param shape, which is bounded by the model, not by
    traffic.  The C001 defect is specifically the PER-LENGTH pattern:
    congruent shapes varying along one effective axis, so groups whose
    variation is not reducible to a single axis (different ranks, or
    multiple uncorrelated varying dims) are not candidates."""
    shapes = sorted(shapes_set)
    flat = []
    for s in shapes:
        row = []
        for dims in s:
            row.extend(dims if isinstance(dims, (tuple, list)) else (dims,))
        flat.append(tuple(row))
    if len({len(r) for r in flat}) != 1:
        return None, []
    varying, values = [], []
    for pos in range(len(flat[0])):
        vals = {r[pos] for r in flat}
        if len(vals) > 1:
            varying.append(pos)
            values.append(vals)
    if len(varying) > 1:
        # multiple varying dims only count as ONE axis when they are
        # perfectly correlated (e.g. several same-length inputs growing
        # together); otherwise the workload is heterogeneous, not
        # unbucketed
        for row in flat:
            if len({row[p] for p in varying}) > 1:
                return None, []
    return varying, values


def check_compiles(ledger: Optional[CompileLedger] = None,
                   shape_churn_threshold: int = 4,
                   static_churn_threshold: int = 3,
                   include_summary: bool = False) -> Report:
    """Analyze a compile ledger (default: the process-wide one); returns
    a Report of C0xx diagnostics located at the call sites that compiled."""
    led = ledger if ledger is not None else get_ledger()
    report = Report()
    stats = led.stats() if include_summary else {}

    for site in led.sites():
        rec = led.site(site)
        misses: List[Miss] = list(rec.misses)
        sigs = [m.signature for m in misses]
        first_site = next((m.callsite for m in misses if m.callsite), None)

        # -- C001 / C004: shape-only cardinality -------------------------
        groups: Dict[Any, List[Miss]] = {}
        for m in misses:
            s = m.signature
            groups.setdefault((s.dtypes, s.weak, s.static),
                              []).append(m)
        for key, members in groups.items():
            shapes_set = {m.signature.shapes for m in members}
            if len(shapes_set) < shape_churn_threshold:
                continue
            varying, values = _shape_deltas(shapes_set)
            if varying is None:
                continue  # heterogeneous group: bounded by the model
            all_vals = [v for vs in values for v in vs]
            bucketed = bool(all_vals) and all(
                isinstance(v, int) and _is_pow2(v) for v in all_vals)
            where = next((m.callsite for m in members if m.callsite),
                         first_site)
            detail = {"site": site, "programs": len(shapes_set),
                      "varying_dims": varying,
                      "observed_values": [sorted(vs, key=repr)[:16]
                                          for vs in values]}
            if bucketed:
                report.add(Diagnostic(
                    _PASS, "C004", Severity.INFO, site,
                    "%d compiled programs at %s differ only in shapes "
                    "whose varying dims are all powers of two — bounded "
                    "bucketed growth (the O(log T) family the discipline "
                    "allows)" % (len(shapes_set), site),
                    location=where, details=detail))
            else:
                report.add(Diagnostic(
                    _PASS, "C001", Severity.ERROR, site,
                    "%d compiled programs at %s differ ONLY in shapes "
                    "(varying dims %s, values %s): per-length compiles "
                    "that should bucket — pad to power-of-two buckets "
                    "(see ShardedDecoder's _bucket) or fix the varying "
                    "dimension" % (
                        len(shapes_set), site, varying,
                        [sorted(vs, key=repr)[:8] for vs in values]),
                    location=where, details=detail))

        # -- C002: dtype / weak-type drift -------------------------------
        seen_pairs = set()
        by_shape_static: Dict[Any, List[Signature]] = {}
        for s in set(sigs):
            by_shape_static.setdefault((s.shapes, s.static),
                                       []).append(s)
        for (shapes, _), members in sorted(by_shape_static.items(),
                                           key=lambda kv: repr(kv[0])):
            if len(members) < 2:
                continue
            dts = {(s.dtypes, s.weak) for s in members}
            if len(dts) < 2:
                continue
            key = (site, shapes)
            if key in seen_pairs:
                continue
            seen_pairs.add(key)
            kinds = []
            if len({s.dtypes for s in members}) > 1:
                kinds.append("dtype")
            if len({s.weak for s in members}) > 1:
                kinds.append("weak_type")
            report.add(Diagnostic(
                _PASS, "C002", Severity.WARNING, site,
                "%d compiled programs at %s share shapes %r but differ "
                "in %s (%s): a python scalar / weak-typed constant is "
                "flipping the signature between calls — pin the dtype "
                "(jnp.float32(x), astype) at the call site" % (
                    len(members), site, shapes, " and ".join(kinds),
                    sorted({s.dtypes for s in members})[:4]),
                location=first_site,
                details={"site": site,
                         "variants": sorted(repr((s.dtypes, s.weak))
                                            for s in members)[:8]}))

        # -- C003: static-kwarg churn ------------------------------------
        by_arrays: Dict[Any, set] = {}
        for s in set(sigs):
            by_arrays.setdefault((s.shapes, s.dtypes, s.weak),
                                 set()).add(s.static)
        for key, statics in sorted(by_arrays.items(),
                                   key=lambda kv: repr(kv[0])):
            if len(statics) < static_churn_threshold:
                continue
            report.add(Diagnostic(
                _PASS, "C003", Severity.WARNING, site,
                "%d compiled programs at %s share identical array "
                "signatures but differ in static parts: a static kwarg "
                "is churning per call — make it a traced array, or "
                "bound its value set" % (len(statics), site),
                location=first_site,
                details={"site": site, "static_variants": len(statics),
                         "sample": sorted(repr(s) for s in statics)[:6]}))

        if include_summary:
            report.add(Diagnostic(
                _PASS, "C005", Severity.INFO, site,
                "%s: %d program(s) compiled, %d hit(s) / %d miss(es), "
                "top shape cardinality %d" % (
                    site, rec.miss_count, rec.hits, rec.miss_count,
                    stats[site]["shape_cardinality"]),
                location=first_site))

    return report


class CompileBudgetExceeded(MXTPUError):
    """Raised by :func:`compile_budget` when a block compiled more
    programs than its budget.  ``compiles`` holds the Miss records."""

    def __init__(self, msg, compiles=None):
        super().__init__(msg)
        self.compiles = list(compiles or [])


@contextlib.contextmanager
def compile_budget(n: int, sites: Optional[tuple] = None,
                   ledger: Optional[CompileLedger] = None):
    """Assert that at most ``n`` new programs are compiled inside the
    block (optionally restricted to ledger ``sites``; a name ending in
    ``*`` matches as a prefix, e.g. ``("serving.*",)``).

    Raises :class:`CompileBudgetExceeded` on exit listing every compile
    with its site, signature, and call site — the O(log T) invariant as
    an executable assertion.  Requires the ledger to be enabled
    (``MXTPU_COMPILE_LEDGER=0`` makes the budget unverifiable, which
    raises immediately rather than silently passing)."""
    led = ledger if ledger is not None else get_ledger()
    if not led.enabled:
        raise MXTPUError(
            "compile_budget needs the compile ledger, but it is "
            "disabled (MXTPU_COMPILE_LEDGER=0) — the budget cannot be "
            "verified")
    before = led.miss_counts(sites)
    seq0 = led.sequence()
    yield led
    new = led.misses_after(seq0, sites)
    total = sum(led.miss_counts(sites).values()) - sum(before.values())
    if total > n:
        lines = ["compile budget exceeded: %d new program(s) compiled, "
                 "budget %d%s" % (total, n,
                                  " (sites %s)" % (sites,) if sites
                                  else "")]
        for m in new[:16]:
            lines.append("  - shapes=%r dtypes=%r at %s" % (
                m.signature.shapes, m.signature.dtypes,
                m.callsite or "<unknown>"))
        if total > len(new):
            lines.append("  (… %d signature(s) dropped by the per-site "
                         "record limit)" % (total - len(new)))
        raise CompileBudgetExceeded("\n".join(lines), compiles=new)


register_pass(_PASS)(check_compiles)
