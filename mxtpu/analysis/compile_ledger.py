"""Process-wide compile ledger: every jit entry point reports into it.

PAPER.md's blueprint makes the compiled graph — not Python dispatch —
the unit of performance, and PERF.md round 5 established the O(log T)
compiled-programs-per-generation discipline every serving and training
win since relies on.  Nothing could *prove* that discipline held: each
subsystem kept a private ``_jit_cache`` dict and regressions (a shape
that should bucket, a static kwarg that churns, a weak-type flip) only
showed up as mysteriously slow runs.

The ledger is the shared observation point.  Every cache-fronted jit
site — the engine's bulk-segment cache, ``CachedOp``, the sharded
decoder's four program kinds (serving bucketed prefill + pooled decode
step), ``SPMDTrainer.step``, and the per-parameter optimizer updates the
gluon ``Trainer`` drives — records each lookup as a :class:`Signature`
(shapes / dtypes / weak-type flags / static parts, pre-split so the
checker can attribute growth to the right component) plus hit/miss and,
for misses, the first non-mxtpu call site.  ``mxtpu.analysis
.compile_check`` turns the record into located C0xx diagnostics and
``compile_budget`` lets tests assert compile counts directly.

Env vars (docs/analysis.md):

- ``MXTPU_COMPILE_LEDGER=0``      disable recording entirely (default on;
  a hit costs two dict operations under a lock).
- ``MXTPU_COMPILE_LEDGER_LIMIT``  max miss records kept per site
  (default 512; further misses are counted but drop their signatures).
- ``MXTPU_COMPILE_LEDGER_DUMP``   path to write the ledger as JSON at
  process exit (``python -m mxtpu.analysis compile DUMP.json`` analyzes
  it offline).

This module must stay import-light (no jax): the engine imports it on
the eager dispatch path.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Any, Dict, Iterable, List, NamedTuple, Optional, Tuple

from ..base import env_bool, env_int

__all__ = ["Signature", "Miss", "SiteRecord", "CompileLedger",
           "get_ledger", "record", "observe", "ledger_enabled"]


class Signature(NamedTuple):
    """One jit-cache key, pre-split into the components the discipline
    checker reasons about.  All fields must be hashable; shapes is a
    tuple of int-tuples, dtypes a tuple of dtype-name strings, weak a
    tuple of bools (weak_type flags, aligned with dtypes where the site
    tracks them), static everything else (op sequences, flags, traced
    python values)."""

    shapes: Tuple[tuple, ...] = ()
    dtypes: Tuple[str, ...] = ()
    weak: Tuple[bool, ...] = ()
    static: Any = ()


class Miss(NamedTuple):
    """One recorded compile (cache miss) at a site."""

    signature: Signature
    callsite: Optional[str]
    seq: int                      # process-wide miss ordinal (event order)


class SiteRecord:
    """Hit/miss history of one jit entry point."""

    __slots__ = ("site", "hits", "miss_count", "misses", "dropped")

    def __init__(self, site: str):
        self.site = site
        self.hits = 0
        self.miss_count = 0
        self.misses: List[Miss] = []
        self.dropped = 0          # misses beyond the per-site limit

    @property
    def lookups(self) -> int:
        return self.hits + self.miss_count

    def to_dict(self) -> Dict[str, Any]:
        return {
            "site": self.site,
            "hits": self.hits,
            "misses": self.miss_count,
            "dropped": self.dropped,
            "signatures": [
                {"shapes": [list(s) for s in m.signature.shapes],
                 "dtypes": list(m.signature.dtypes),
                 "weak": list(m.signature.weak),
                 "static": repr(m.signature.static),
                 "callsite": m.callsite,
                 "seq": m.seq}
                for m in self.misses],
        }


def _first_external_callsite() -> Optional[str]:
    """file:line of the innermost frame OUTSIDE the mxtpu package — the
    user code that triggered this compile.  Only runs on a miss, where a
    real compile (orders of magnitude more expensive) follows anyway."""
    import traceback

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for frame in reversed(traceback.extract_stack()[:-2]):
        fname = os.path.abspath(frame.filename)
        if not fname.startswith(pkg_dir + os.sep):
            return "%s:%d" % (frame.filename, frame.lineno)
    return None


class CompileLedger:
    """Thread-safe registry of per-site compile histories."""

    def __init__(self, enabled: Optional[bool] = None,
                 miss_limit: Optional[int] = None):
        self._enabled = (env_bool("MXTPU_COMPILE_LEDGER", default=True)
                         if enabled is None else bool(enabled))
        self._miss_limit = (env_int("MXTPU_COMPILE_LEDGER_LIMIT", 512)
                            if miss_limit is None else int(miss_limit))
        self._lock = threading.Lock()
        self._sites: Dict[str, SiteRecord] = {}
        self._seen: Dict[str, set] = {}   # observe()'s per-site key sets
        self._seq = 0

    # -- recording -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def record(self, site: str, signature: Signature, hit: bool,
               callsite: Optional[str] = None) -> None:
        """Report one jit-cache lookup.  ``hit=False`` means a new
        program was (or is about to be) compiled for this signature."""
        if not self._enabled:
            return
        if hit:
            with self._lock:
                rec = self._sites.get(site)
                if rec is None:
                    rec = self._sites[site] = SiteRecord(site)
                rec.hits += 1
            return
        # miss: callsite capture outside the lock (stack walk)
        if callsite is None:
            callsite = _first_external_callsite()
        with self._lock:
            rec = self._sites.get(site)
            if rec is None:
                rec = self._sites[site] = SiteRecord(site)
            rec.miss_count += 1
            self._seq += 1
            if len(rec.misses) < self._miss_limit:
                rec.misses.append(Miss(signature, callsite, self._seq))
            else:
                rec.dropped += 1

    def observe(self, site: str, signature: Signature,
                callsite: Optional[str] = None) -> bool:
        """Record a lookup at a site with no inspectable cache of its own
        (e.g. the optimizer's per-parameter jitted updates, where jax.jit
        keeps the executable cache internally): the ledger tracks the
        seen-signature set itself.  Returns True on hit."""
        if not self._enabled:
            return True
        with self._lock:
            seen = self._seen.setdefault(site, set())
            hit = signature in seen
            if not hit:
                seen.add(signature)
        self.record(site, signature, hit, callsite=callsite)
        return hit

    # -- querying --------------------------------------------------------
    def sites(self) -> List[str]:
        with self._lock:
            return sorted(self._sites)

    def site(self, name: str) -> Optional[SiteRecord]:
        with self._lock:
            return self._sites.get(name)

    def miss_counts(self, sites: Optional[Iterable[str]] = None) \
            -> Dict[str, int]:
        """site -> miss count (compiled programs), optionally filtered to
        site names or prefixes (a name ending in '*' matches as prefix)."""
        with self._lock:
            out = {}
            for name, rec in self._sites.items():
                if sites is not None and not _site_match(name, sites):
                    continue
                out[name] = rec.miss_count
            return out

    def sequence(self) -> int:
        """Current process-wide miss ordinal — snapshot it before a
        block and pass to :meth:`misses_after` to select exactly the
        compiles that happened inside (count-based slicing would hand
        back stale pre-snapshot records once the per-site record limit
        drops new signatures)."""
        with self._lock:
            return self._seq

    def misses_after(self, seq: int,
                     sites: Optional[Iterable[str]] = None) -> List[Miss]:
        """Miss records strictly newer than a :meth:`sequence`
        watermark (records dropped by the per-site limit are absent —
        compare counts via :meth:`miss_counts` for the true total)."""
        with self._lock:
            out = []
            for name, rec in self._sites.items():
                if sites is not None and not _site_match(name, sites):
                    continue
                out.extend(m for m in rec.misses if m.seq > seq)
            return sorted(out, key=lambda m: m.seq)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-site counters for diagnose/bench: lookups, hits, misses,
        distinct signature count, and top signature-cardinality group."""
        with self._lock:
            out = {}
            for name, rec in sorted(self._sites.items()):
                sigs = [m.signature for m in rec.misses]
                out[name] = {
                    "lookups": rec.lookups,
                    "hits": rec.hits,
                    "misses": rec.miss_count,
                    "distinct_signatures": len(set(sigs)),
                    "shape_cardinality": _top_shape_cardinality(sigs),
                }
            return out

    def total_compiles(self) -> int:
        with self._lock:
            return sum(r.miss_count for r in self._sites.values())

    def reset(self) -> None:
        with self._lock:
            self._sites.clear()
            self._seen.clear()
            self._seq = 0

    # -- persistence -----------------------------------------------------
    def to_json(self) -> str:
        with self._lock:
            return json.dumps(
                {"version": 1,
                 "sites": [r.to_dict()
                           for _, r in sorted(self._sites.items())]},
                indent=2)

    @classmethod
    def from_json(cls, text: str) -> "CompileLedger":
        data = json.loads(text)
        led = cls(enabled=True)
        for site in data.get("sites", ()):
            rec = SiteRecord(site["site"])
            rec.hits = int(site.get("hits", 0))
            rec.miss_count = int(site.get("misses", 0))
            rec.dropped = int(site.get("dropped", 0))
            for s in site.get("signatures", ()):
                sig = Signature(
                    shapes=tuple(tuple(x) for x in s.get("shapes", ())),
                    dtypes=tuple(s.get("dtypes", ())),
                    weak=tuple(bool(w) for w in s.get("weak", ())),
                    static=s.get("static", ""))
                rec.misses.append(Miss(sig, s.get("callsite"),
                                       int(s.get("seq", 0))))
            led._sites[rec.site] = rec
        return led


def _site_match(name: str, sites: Iterable[str]) -> bool:
    for s in sites:
        if s.endswith("*"):
            if name.startswith(s[:-1]):
                return True
        elif name == s:
            return True
    return False


def _top_shape_cardinality(sigs: List[Signature]) -> int:
    """Largest count of distinct shape tuples among signatures agreeing
    on everything else — the number the bucketing discipline bounds."""
    groups: Dict[Any, set] = {}
    for s in sigs:
        groups.setdefault((s.dtypes, s.weak, s.static),
                          set()).add(s.shapes)
    return max((len(v) for v in groups.values()), default=0)


_LEDGER = CompileLedger()


def get_ledger() -> CompileLedger:
    """The process-wide ledger instance."""
    return _LEDGER


def ledger_enabled() -> bool:
    return _LEDGER.enabled


def record(site: str, signature: Signature, hit: bool,
           callsite: Optional[str] = None) -> None:
    """Module-level convenience for instrumented jit sites."""
    _LEDGER.record(site, signature, hit, callsite=callsite)


def observe(site: str, signature: Signature,
            callsite: Optional[str] = None) -> bool:
    return _LEDGER.observe(site, signature, callsite=callsite)


_dump_path = os.environ.get("MXTPU_COMPILE_LEDGER_DUMP")
if _dump_path:
    def _dump_at_exit(path=_dump_path):
        try:
            with open(path, "w") as f:
                f.write(_LEDGER.to_json())
        except OSError:
            pass
    atexit.register(_dump_at_exit)
