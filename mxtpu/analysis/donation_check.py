"""donation_check: verify buffer donation actually aliases, and flag
missed donation opportunities.

``donate_argnums`` is a *request*: XLA only aliases a donated input to
an output with the same shape+dtype, and a donation that cannot alias is
silently dropped (jax prints one easily-missed UserWarning and the
program quietly doubles its parameter residency).  The inverse failure
is quieter still: a trainer step that passes params/optimizer state
undonated holds two full copies of the model across every update —
ROADMAP item 5 (whole-loop scan capture with donation) is built on
catching exactly that.

The pass checks three layers:

1. **Aval matching** — the same shape+dtype greedy matching XLA's
   aliasing pass performs, over the flattened donated leaves vs the
   outputs.  Platform-independent.
2. **Lowered aliasing attributes** — ``tf.aliasing_output`` per entry
   parameter in the lowered StableHLO: what lowering actually recorded.
3. **Compiled executable** — ``input_output_alias`` in the optimized
   HLO plus ``memory_analysis().alias_size_in_bytes``: what the
   executable will really do (skipped with ``compile=False``).

==========  ========  =====================================================
code        severity  meaning
==========  ========  =====================================================
D001        ERROR     a donated argument does not alias any output in the
                      compiled program (donation silently dropped)
D002        WARNING   missed donation: an undonated argument's leaves all
                      match leftover outputs exactly (params/opt-state
                      passed undonated)
D003        INFO      donation verified: n leaves aliased, bytes saved
D004        INFO      executable-level verification unavailable on this
                      backend (aval-level result stands)
==========  ========  =====================================================

``check_trainer_donation(trainer, data, label)`` applies the pass to an
``SPMDTrainer``'s compiled step (donate_argnums ``(0, 1, 2)`` — params,
aux, optimizer state); tests seed a ``donate=False`` trainer and assert
the D002s name the undonated state.  ``n_steps=N`` checks the fused
N-step scan window instead: the donated state becomes the scan's loop
carry and the proof covers the whole window program (D003 carries a
``loop_carried`` detail + message note).
"""

from __future__ import annotations

import re
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .diagnostics import Diagnostic, Report, Severity, register_pass
from .memory_estimate import format_bytes

__all__ = ["check_donation", "check_trainer_donation"]

_PASS = "donation_check"

_ARG_SPLIT = re.compile(r"%arg(\d+)")
_ALIAS_NUM = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")


def _aval_of(x) -> Tuple[tuple, str]:
    return (tuple(getattr(x, "shape", ())),
            str(getattr(x, "dtype", "float32")))


def _nbytes(aval: Tuple[tuple, str]) -> int:
    import jax.numpy as jnp
    n = 1
    for d in aval[0]:
        n *= int(d)
    try:
        return n * jnp.dtype(aval[1]).itemsize
    except TypeError:
        return n * 4


def _lowered_alias_map(lowered_text: str) -> Dict[int, int]:
    """flat entry-parameter index -> aliased output index, parsed from
    the lowered StableHLO's ``tf.aliasing_output`` arg attributes.

    Attribute dicts can nest braces inside quoted strings
    (``mhlo.sharding = "{replicated}"``), so instead of matching the
    ``{...}`` dict, split the module text on ``%argN`` references: the
    aliasing attribute of arg N, when present, sits between its
    signature occurrence and the next ``%arg`` (body uses of ``%argN``
    carry no attributes, and first-win keeps the signature's)."""
    out = {}
    parts = _ARG_SPLIT.split(lowered_text)
    # parts = [prefix, argidx, chunk, argidx, chunk, ...]
    for i in range(1, len(parts) - 1, 2):
        idx = int(parts[i])
        if idx in out:
            continue
        am = _ALIAS_NUM.search(parts[i + 1])
        if am:
            out[idx] = int(am.group(1))
    return out


def check_donation(fn, *sample_args, donate_argnums: Sequence[int] = (),
                   donatable_argnums: Optional[Sequence[int]] = None,
                   static_argnums: Sequence[int] = (),
                   in_shardings=None, out_shardings=None,
                   compile: bool = True,
                   arg_names: Optional[Sequence[str]] = None) -> Report:
    """Check donation/aliasing of one jittable callable on sample
    arguments (abstract or concrete; never executes).

    donate_argnums: what the caller donates (the claim under test).
    donatable_argnums: arguments that COULD be donated — dead after the
    call from the caller's point of view (default: every non-static,
    non-donated argument); only these produce D002.
    arg_names: display names per argnum (defaults to ``arg<i>``).
    """
    import jax

    report = Report()
    statics = set(static_argnums)
    names = list(arg_names) if arg_names is not None else [
        "arg%d" % i for i in range(len(sample_args))]

    # flat leaf index ranges per top-level argnum (jit's flattening order)
    flat: List[Tuple[int, Tuple[tuple, str]]] = []
    arg_leaf_idx: Dict[int, List[int]] = {}
    for i, a in enumerate(sample_args):
        if i in statics:
            continue
        for leaf in jax.tree_util.tree_leaves(a):
            arg_leaf_idx.setdefault(i, []).append(len(flat))
            flat.append((i, _aval_of(leaf)))

    kw = {}
    if in_shardings is not None:
        kw["in_shardings"] = in_shardings
    if out_shardings is not None:
        kw["out_shardings"] = out_shardings
    if hasattr(fn, "lower") and not kw and not static_argnums:
        # already a jit-staged callable (e.g. a trainer's compiled step):
        # lower IT directly — wrapping it in another jax.jit would lower
        # the outer call without the inner stage's aliasing attributes,
        # and donate_argnums here describes the claim being verified
        jitted = fn
    else:
        jitted = jax.jit(fn, donate_argnums=tuple(donate_argnums),
                         static_argnums=tuple(static_argnums), **kw)
    with warnings.catch_warnings(record=True) as wrec:
        warnings.simplefilter("always")
        lowered = jitted.lower(*sample_args)
    drop_warnings = [str(w.message) for w in wrec
                     if "donated buffers were not usable" in
                     str(w.message)
                     or "onation is not implemented" in str(w.message)]

    out_avals = [_aval_of(o) for o in
                 jax.tree_util.tree_leaves(lowered.out_info)]

    lowered_text = lowered.as_text()
    alias_map = _lowered_alias_map(lowered_text)
    backend_unverifiable = any("onation is not implemented" in w
                               for w in drop_warnings)
    # loop-carried program (lax.scan / while_loop): the aliasing proof
    # below then covers the donated buffers THROUGH the loop carry —
    # the whole fused window updates in place, not just a flat step
    loop_carried = ("stablehlo.while" in lowered_text
                    or "mhlo.while" in lowered_text)

    # -- aval-level greedy matching (XLA's aliasing rule) ----------------
    remaining = list(range(len(out_avals)))

    def take_match(aval):
        for k in remaining:
            if out_avals[k] == aval:
                remaining.remove(k)
                return k
        return None

    donated = sorted(set(donate_argnums) - statics)
    aliased_leaves = 0
    aliased_bytes = 0
    for argnum in donated:
        leaf_idxs = arg_leaf_idx.get(argnum, [])
        dead = []
        for li in leaf_idxs:
            aval = flat[li][1]
            matched = take_match(aval)
            in_exec = li in alias_map
            if in_exec:
                aliased_leaves += 1
                aliased_bytes += _nbytes(aval)
            elif matched is None:
                dead.append((li, aval))
            elif not backend_unverifiable:
                # an output matched but lowering did not alias it —
                # donation dropped (consumed elsewhere / ordering)
                dead.append((li, aval))
            else:
                aliased_leaves += 1  # aval-level only (D004 notes it)
                aliased_bytes += _nbytes(aval)
        if dead:
            report.add(Diagnostic(
                _PASS, "D001", Severity.ERROR, names[argnum],
                "donated argument %s: %d of %d leaves do not alias any "
                "output (e.g. %s %s) — the donation is silently dropped "
                "and the buffer stays resident; donate only buffers "
                "whose shape+dtype match an output%s" % (
                    names[argnum], len(dead), len(leaf_idxs),
                    dead[0][1][1], dead[0][1][0],
                    "; jax: %s" % drop_warnings[0].split("\n")[0][:160]
                    if drop_warnings else ""),
                details={"argnum": argnum,
                         "dead_leaves": [list(map(str, d[1]))
                                         for d in dead[:8]]}))

    # -- missed opportunities --------------------------------------------
    if donatable_argnums is None:
        donatable = [i for i in range(len(sample_args))
                     if i not in statics and i not in set(donated)]
    else:
        donatable = [i for i in donatable_argnums
                     if i not in statics and i not in set(donated)]
    for argnum in donatable:
        leaf_idxs = arg_leaf_idx.get(argnum, [])
        if not leaf_idxs:
            continue
        trial = list(remaining)
        matches = 0
        saved = 0
        for li in leaf_idxs:
            aval = flat[li][1]
            for k in trial:
                if out_avals[k] == aval:
                    trial.remove(k)
                    matches += 1
                    saved += _nbytes(aval)
                    break
        if matches == len(leaf_idxs) and matches > 0:
            # every leaf of the argument matches a leftover output:
            # donating it would alias in full
            for li in leaf_idxs:
                remaining.remove(next(
                    k for k in remaining
                    if out_avals[k] == flat[li][1]))
            report.add(Diagnostic(
                _PASS, "D002", Severity.WARNING, names[argnum],
                "argument %s (%d leaves, %s) is passed undonated but "
                "every leaf matches an output exactly — donating it "
                "would update in place and halve its residency "
                "(donate_argnums)" % (names[argnum], matches,
                                      format_bytes(saved)),
                details={"argnum": argnum, "leaves": matches,
                         "bytes": saved}))

    # -- executable-level confirmation -----------------------------------
    if backend_unverifiable:
        report.add(Diagnostic(
            _PASS, "D004", Severity.INFO, "backend",
            "this backend does not implement buffer donation — "
            "executable-level aliasing cannot be verified here; the "
            "aval-level verdicts above stand"))
    elif donated:
        exec_aliases = None
        if compile:
            compiled = lowered.compile()
            txt = compiled.as_text() or ""
            exec_aliases = "input_output_alias" in txt
            try:
                alias_bytes = int(
                    compiled.memory_analysis().alias_size_in_bytes)
            except Exception:
                alias_bytes = None
        else:
            alias_bytes = None
        if aliased_leaves:
            report.add(Diagnostic(
                _PASS, "D003", Severity.INFO, "donation",
                "%d donated leaf/leaves alias outputs (%s saved)%s%s" % (
                    aliased_leaves, format_bytes(aliased_bytes),
                    "; aliasing holds through the loop-carried (scan) "
                    "program" if loop_carried else "",
                    {True: "; executable confirms input_output_alias",
                     False: "; executable shows NO input_output_alias",
                     None: ""}[exec_aliases]),
                details={"leaves": aliased_leaves,
                         "bytes": aliased_bytes,
                         "alias_bytes": alias_bytes,
                         "loop_carried": loop_carried}))
            if exec_aliases is False:
                report.add(Diagnostic(
                    _PASS, "D001", Severity.ERROR, "donation",
                    "lowering recorded aliasing but the compiled "
                    "executable has no input_output_alias — donation "
                    "was dropped during compilation"))
    return report


def check_trainer_donation(trainer, data, label,
                           compile: bool = True,
                           n_steps: Optional[int] = None) -> Report:
    """Apply :func:`check_donation` to an ``SPMDTrainer``'s compiled
    step.  Stages the trainer if needed (one imperative forward) and
    lowers the step abstractly — no training step executes.
    ``compile=False`` stops at the lowered aliasing attributes (cheaper;
    skips the executable-level confirmation).

    ``n_steps=N`` (N > 1) checks the fused N-step ``lax.scan`` window
    program (docs/training.md) instead of the flat step: the donated
    params / aux / optimizer state become scan loop carries, and the
    same three-layer proof (aval matching, ``tf.aliasing_output``,
    executable ``input_output_alias``) must show the window's inputs
    aliasing its outputs — i.e. the whole fused window updates in
    place.  Only the shapes matter, so the window's batch/label/key
    stacks are abstract (``jax.ShapeDtypeStruct``); nothing executes.

    donate=True trainers must verify clean (D003, with the
    loop-carried note for windows); donate=False trainers get one D002
    per undonated state argument — params, aux and optimizer state each
    held twice per step."""
    import jax
    import jax.numpy as jnp

    from .. import ndarray as nd
    from .. import random as _random

    data = data if isinstance(data, nd.NDArray) else nd.array(data)
    label = label if isinstance(label, nd.NDArray) else nd.array(label)
    trainer._ensure_staged(data)
    if trainer._guard and trainer._scale_state is None:
        trainer._scale_state = trainer._init_scale_state()

    batch = data._data
    lab = label._data
    sig = (tuple(batch.shape), str(batch.dtype), tuple(lab.shape),
           str(lab.dtype))
    diff_leaves = tuple(p.data()._data for p in trainer._diff_params)
    aux_leaves = tuple(p.data()._data for p in trainer._aux_params)
    donated = (0, 1, 2) if trainer._donate else ()

    n = int(n_steps) if n_steps else 1
    if n > 1:
        step_fn = trainer._build_multi_step(n, *sig)
        # abstract window stacks: lowering only needs avals, and a
        # ShapeDtypeStruct key stack would lose the PRNG dtype — split
        # a throwaway root instead (never consumed from the ring)
        batches = jax.ShapeDtypeStruct((n,) + sig[0], sig[1])
        labels = jax.ShapeDtypeStruct((n,) + sig[2], sig[3])
        keys = jax.random.split(jax.random.key(0), n)
        lrs = jnp.zeros((n,), jnp.float32)
        if trainer._guard:
            args = [diff_leaves, aux_leaves, tuple(trainer._opt_states),
                    trainer._scale_state, lrs, jnp.float32(0.0),
                    batches, labels, keys]
            names = ["params", "aux_params", "opt_states",
                     "scale_state", "lrs", "t0", "batches", "labels",
                     "rng_keys"]
        else:
            args = [diff_leaves, aux_leaves, tuple(trainer._opt_states),
                    lrs, jnp.zeros((n,), jnp.float32), batches, labels,
                    keys]
            names = ["params", "aux_params", "opt_states", "lrs", "ts",
                     "batches", "labels", "rng_keys"]
        return check_donation(
            step_fn, *args, donate_argnums=donated,
            donatable_argnums=(0, 1, 2), arg_names=names,
            compile=compile)

    step_fn = trainer._build_step(*sig)
    args = [diff_leaves, aux_leaves, tuple(trainer._opt_states),
            jnp.float32(trainer._effective_lr()), jnp.float32(1.0),
            batch, lab, _random.next_key()]
    names = ["params", "aux_params", "opt_states", "lr", "t", "batch",
             "label", "rng_key"]
    if trainer._guard:
        args.append(trainer._scale_state)
        names.append("scale_state")

    # step_fn is already a jax.jit stage with its donate/shardings baked
    # in; re-wrap the underlying behavior by checking THROUGH it: lower
    # directly and reuse check_donation's parsing on the lowered text.
    report = check_donation(
        step_fn, *args, donate_argnums=donated,
        donatable_argnums=(0, 1, 2), arg_names=names, compile=compile)
    return report


register_pass(_PASS)(check_donation)
