"""mx.nd.save / mx.nd.load (parity: src/ndarray/ndarray.cc NDArray::Save/
Load via MXNDArraySave/MXNDArrayLoad — the container format behind
``.params`` checkpoints).

Two formats:
 - native "MXTP" container (written by default): 16-byte header, JSON index,
   raw little-endian buffers.  Self-describing and mmap-friendly.
 - legacy MXNet 1.x binary (magic 0x112 list header + per-array V2 blocks):
   best-effort *reader* for interop with reference-produced .params files.
   The exact reference layout could not be verified against the mount
   (SURVEY.md §0); the reader fails with a clear error rather than
   misparsing.

Robustness (docs/guardian.md): ``save`` writes atomically (tmp + fsync +
rename) with a per-tensor CRC32 manifest sidecar via
:mod:`mxtpu.resilience.checkpoint`, so a crash mid-save can never leave
a truncated file at the final path.  ``load`` verifies the manifest when
present, and every parse failure — truncation, bad magic, short payload
— raises a typed :class:`~mxtpu.resilience.CorruptCheckpointError`
naming the file and byte offset instead of a raw ``struct.error`` or a
silent misparse.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, List, Union

import numpy as onp

from .ndarray import NDArray, array

_MAGIC = b"MXTP0001"
_LEGACY_LIST_MAGIC = 0x112
_LEGACY_ND_MAGIC = 0xF993FAC9

_DTYPE_FLAG = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
               4: "int32", 5: "int8", 6: "int64"}


def _ckpt():
    from ..resilience import checkpoint
    return checkpoint


def save(fname: str, data):
    """Save NDArrays: list -> unnamed, dict -> named (parity mx.nd.save).
    Atomic, with a CRC32 manifest sidecar (``<fname>.mxmf``)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    np_arrays = [a.asnumpy() if isinstance(a, NDArray) else onp.asarray(a)
                 for a in arrays]
    index = {
        "names": names,
        "arrays": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                   for a in np_arrays],
    }
    blob = json.dumps(index).encode()
    header = _MAGIC + struct.pack("<Q", len(blob)) + blob
    tensors = []

    def chunks():
        # streamed into write_verified one tensor at a time — the whole
        # payload is never resident (matters exactly when checkpointing
        # under memory pressure, e.g. a preemption save)
        yield header
        off = len(header)
        for i, a in enumerate(np_arrays):
            b = onp.ascontiguousarray(a).tobytes()
            tensors.append({"name": names[i] if names else str(i),
                            "offset": off, "size": len(b),
                            "crc32": zlib.crc32(b) & 0xFFFFFFFF})
            off += len(b)
            yield b

    _ckpt().write_verified(fname, chunks(), tensors=tensors)


def load(fname: str) -> Union[List[NDArray], Dict[str, NDArray]]:
    import mmap

    ckpt = _ckpt()
    try:
        with open(fname, "rb") as f:
            # mmap, not read(): restore peak memory stays bounded (the
            # page cache backs the map) — a multi-GB checkpoint is never
            # resident as one buffer, which matters exactly when
            # restoring under memory pressure after a preemption.  An
            # empty file cannot be mapped; b"" takes the same typed
            # truncation path below.
            try:
                buf = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
            except ValueError:
                buf = b""
    except FileNotFoundError:
        ckpt.verify(fname)  # typed "file missing" when a manifest exists
        raise
    # CRC check when a manifest sidecar exists — zlib.crc32 streams
    # through the map without materializing it
    ckpt.verify(fname, data=buf)

    def corrupt(msg, offset):
        raise ckpt.CorruptCheckpointError(msg, path=fname, offset=offset)

    if len(buf) < 8:
        corrupt("truncated NDArray file: %d bytes, header needs 8"
                % len(buf), len(buf))
    if buf[:8] == _MAGIC:
        if len(buf) < 16:
            corrupt("truncated MXTP header: %d bytes, need 16" % len(buf),
                    len(buf))
        (n,) = struct.unpack_from("<Q", buf, 8)
        if 16 + n > len(buf):
            corrupt("truncated MXTP index: need %d bytes, file has %d"
                    % (16 + n, len(buf)), len(buf))
        try:
            index = json.loads(buf[16:16 + n])
            metas = index["arrays"]
            names = index["names"]
        except (ValueError, KeyError, TypeError):
            corrupt("MXTP index is not parseable JSON", 16)
        off = 16 + n
        out = []
        for i, meta in enumerate(metas):
            nm = (names[i] if isinstance(names, list) and i < len(names)
                  else i)
            try:
                # a bit flip INSIDE still-parseable JSON (e.g. a mangled
                # dtype string or a non-int shape entry) must surface as
                # the typed error too, not a bare TypeError/KeyError
                dt = onp.dtype(meta["dtype"])
                shape = tuple(int(d) for d in meta["shape"])
                count = int(onp.prod(shape)) if shape else 1
            except (KeyError, TypeError, ValueError):
                corrupt("MXTP index entry %d (%r) is malformed" % (i, nm),
                        16)
            nbytes = count * dt.itemsize
            if off + nbytes > len(buf):
                corrupt("short payload for tensor %d (%r): needs bytes "
                        "[%d, %d) but file ends at %d"
                        % (i, nm, off, off + nbytes, len(buf)), len(buf))
            out.append(array(onp.frombuffer(
                buf, dtype=dt, count=count, offset=off).reshape(shape)))
            off += nbytes
        if names:
            return dict(zip(names, out))
        return out
    try:
        return _load_legacy(buf, fname)
    except struct.error as e:
        # every struct.unpack_from failure is an out-of-bounds read —
        # a truncated or damaged legacy file, never a caller bug
        raise ckpt.CorruptCheckpointError(
            "truncated legacy NDArray file (%s)" % e, path=fname,
            offset=len(buf)) from None
    except UnicodeDecodeError as e:
        # a flipped byte inside a stored name: damage, typed like the rest
        raise ckpt.CorruptCheckpointError(
            "undecodable name in legacy NDArray file (%s)" % e,
            path=fname, offset=len(buf)) from None


def _load_legacy(buf: bytes, fname: str = "<bytes>"):
    from ..resilience.checkpoint import CorruptCheckpointError

    off = 0

    def u64():
        nonlocal off
        (v,) = struct.unpack_from("<Q", buf, off)
        off += 8
        return v

    def u32():
        nonlocal off
        (v,) = struct.unpack_from("<I", buf, off)
        off += 4
        return v

    def i32():
        nonlocal off
        (v,) = struct.unpack_from("<i", buf, off)
        off += 4
        return v

    magic = u64()
    if magic != _LEGACY_LIST_MAGIC:
        raise CorruptCheckpointError(
            f"unrecognised NDArray file (magic {magic:#x}); neither MXTP "
            "nor legacy MXNet format", path=fname, offset=0)
    u64()  # reserved
    n = u64()
    arrays = []
    for _ in range(n):
        block_off = off
        m = u32()
        if m != _LEGACY_ND_MAGIC:
            raise CorruptCheckpointError(
                "legacy NDArray block magic mismatch — reference layout "
                "differs from the documented V2 format; cannot load",
                path=fname, offset=block_off)
        stype = i32()
        if stype not in (-1, 0):  # kDefaultStorage / dense marker
            raise ValueError("sparse legacy arrays unsupported (descoped)")
        ndim = i32()
        shape = [i32() for _ in range(ndim)]
        i32()  # dev_type
        i32()  # dev_id
        dtype_flag = i32()
        if dtype_flag not in _DTYPE_FLAG:
            # a damaged flag must not silently reinterpret the payload
            # as float32 — wrong dtype + wrong itemsize = garbage weights
            raise CorruptCheckpointError(
                "unknown dtype flag %d in legacy NDArray block"
                % dtype_flag, path=fname, offset=off - 4)
        dt = onp.dtype(_DTYPE_FLAG[dtype_flag])
        count = int(onp.prod(shape)) if shape else 1
        if off + count * dt.itemsize > len(buf):
            raise CorruptCheckpointError(
                "short payload in legacy NDArray block: needs %d bytes "
                "at offset %d but file ends at %d"
                % (count * dt.itemsize, off, len(buf)), path=fname,
                offset=len(buf))
        a = onp.frombuffer(buf, dtype=dt, count=count, offset=off).reshape(shape)
        off += count * dt.itemsize
        arrays.append(array(a))
    nk = u64()
    names = []
    for _ in range(nk):
        ln = u64()
        if off + ln > len(buf):
            raise CorruptCheckpointError(
                "short name table in legacy NDArray file", path=fname,
                offset=len(buf))
        names.append(buf[off:off + ln].decode())
        off += ln
    if names:
        return dict(zip(names, arrays))
    return arrays
