"""mx.nd.save / mx.nd.load (parity: src/ndarray/ndarray.cc NDArray::Save/
Load via MXNDArraySave/MXNDArrayLoad — the container format behind
``.params`` checkpoints).

Two formats:
 - native "MXTP" container (written by default): 16-byte header, JSON index,
   raw little-endian buffers.  Self-describing and mmap-friendly.
 - legacy MXNet 1.x binary (magic 0x112 list header + per-array V2 blocks):
   best-effort *reader* for interop with reference-produced .params files.
   The exact reference layout could not be verified against the mount
   (SURVEY.md §0); the reader fails with a clear error rather than
   misparsing.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Union

import numpy as onp

from .ndarray import NDArray, array

_MAGIC = b"MXTP0001"
_LEGACY_LIST_MAGIC = 0x112
_LEGACY_ND_MAGIC = 0xF993FAC9

_DTYPE_FLAG = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
               4: "int32", 5: "int8", 6: "int64"}


def save(fname: str, data):
    """Save NDArrays: list -> unnamed, dict -> named (parity mx.nd.save)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    np_arrays = [a.asnumpy() if isinstance(a, NDArray) else onp.asarray(a)
                 for a in arrays]
    index = {
        "names": names,
        "arrays": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                   for a in np_arrays],
    }
    blob = json.dumps(index).encode()
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        for a in np_arrays:
            f.write(onp.ascontiguousarray(a).tobytes())


def load(fname: str) -> Union[List[NDArray], Dict[str, NDArray]]:
    with open(fname, "rb") as f:
        head = f.read(8)
        if head == _MAGIC:
            (n,) = struct.unpack("<Q", f.read(8))
            index = json.loads(f.read(n))
            out = []
            for meta in index["arrays"]:
                dt = onp.dtype(meta["dtype"])
                count = int(onp.prod(meta["shape"])) if meta["shape"] else 1
                buf = f.read(count * dt.itemsize)
                out.append(array(onp.frombuffer(buf, dtype=dt).reshape(
                    meta["shape"])))
            if index["names"]:
                return dict(zip(index["names"], out))
            return out
        # legacy path
        f.seek(0)
        return _load_legacy(f.read())


def _load_legacy(buf: bytes):
    off = 0

    def u64():
        nonlocal off
        (v,) = struct.unpack_from("<Q", buf, off)
        off += 8
        return v

    def u32():
        nonlocal off
        (v,) = struct.unpack_from("<I", buf, off)
        off += 4
        return v

    def i32():
        nonlocal off
        (v,) = struct.unpack_from("<i", buf, off)
        off += 4
        return v

    magic = u64()
    if magic != _LEGACY_LIST_MAGIC:
        raise ValueError(
            f"unrecognised NDArray file (magic {magic:#x}); neither MXTP "
            "nor legacy MXNet format")
    u64()  # reserved
    n = u64()
    arrays = []
    for _ in range(n):
        m = u32()
        if m != _LEGACY_ND_MAGIC:
            raise ValueError(
                "legacy NDArray block magic mismatch — reference layout "
                "differs from the documented V2 format; cannot load")
        stype = i32()
        if stype not in (-1, 0):  # kDefaultStorage / dense marker
            raise ValueError("sparse legacy arrays unsupported (descoped)")
        ndim = i32()
        shape = [i32() for _ in range(ndim)]
        i32()  # dev_type
        i32()  # dev_id
        dtype_flag = i32()
        dt = onp.dtype(_DTYPE_FLAG.get(dtype_flag, "float32"))
        count = int(onp.prod(shape)) if shape else 1
        a = onp.frombuffer(buf, dtype=dt, count=count, offset=off).reshape(shape)
        off += count * dt.itemsize
        arrays.append(array(a))
    nk = u64()
    names = []
    for _ in range(nk):
        ln = u64()
        names.append(buf[off:off + ln].decode())
        off += ln
    if names:
        return dict(zip(names, arrays))
    return arrays
