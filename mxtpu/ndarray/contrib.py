"""mx.nd.contrib: control-flow wrappers with the reference calling
convention (parity: python/mxnet/ndarray/contrib.py foreach/while_loop/
cond), plus flat access to every _contrib_* registry op via the parent
namespace.

The wrappers reconstruct MXNet's (outputs, states) return structure from
the flat tuple the registry ops produce; the body's output arity is
captured during the first (tracing) call.
"""

from __future__ import annotations

from .ndarray import NDArray, invoke_op

__all__ = ["foreach", "while_loop", "cond"]


def _tolist(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _check_taped_closures(opname, *fns):
    """Gradients flow only to explicit array inputs (data/states/inputs) —
    the scan/cond is differentiated as one op via jax.vjp, so an NDArray
    captured by closure enters the trace as a constant.  The reference's
    imperative control flow runs eagerly and closure gradients flow there;
    failing loudly beats silently-zero grads."""
    from .. import autograd
    if not autograd.is_recording():
        return
    for fn in fns:
        seen = []
        for cell in (getattr(fn, "__closure__", None) or ()):
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            vals = v if isinstance(v, (list, tuple)) else [v]
            for item in vals:
                if isinstance(item, NDArray) and autograd._on_tape(item):
                    seen.append(item)
        if seen:
            raise ValueError(
                "%s: the body/branch callable captures %d NDArray(s) that "
                "are on the autograd tape; gradients cannot flow to "
                "closure captures (the loop is differentiated as one op). "
                "Pass them through init_states/loop_vars/inputs instead "
                "— loop-invariant states thread through unchanged."
                % (opname, len(seen)))


def foreach(body, data, init_states, name=None):
    """Scan body over the leading axis (parity: nd.contrib.foreach).

    body(data_slice, states) -> (outputs, new_states); returns
    (outputs, final_states) with the same nesting the body used.
    """
    _check_taped_closures("foreach", body)
    data_l = _tolist(data)
    states_l = _tolist(init_states)
    arity = {}

    def body2(d, s):
        outs, ns = body(d, s)
        arity["out_single"] = isinstance(outs, NDArray)
        arity["n_out"] = 1 if arity["out_single"] else len(outs)
        return outs, ns

    flat = invoke_op("foreach", tuple(data_l) + tuple(states_l),
                     {"body": body2, "num_data": len(data_l)})
    flat = list(flat) if isinstance(flat, tuple) else [flat]
    n_out = arity["n_out"]
    outs = flat[:n_out]
    states = flat[n_out:]
    outs = outs[0] if arity["out_single"] else outs
    # states mirror the nesting of init_states (reference contract)
    if not isinstance(init_states, (list, tuple)):
        states = states[0]
    elif isinstance(init_states, tuple):
        states = tuple(states)
    return outs, states


def while_loop(cond, func, loop_vars, max_iterations=None, name=None):
    """parity: nd.contrib.while_loop.  func(*loop_vars) ->
    (step_outputs, new_loop_vars); returns (stacked_outputs,
    final_loop_vars); output rows past termination are zeros (the
    reference leaves them undefined)."""
    if max_iterations is None:
        raise ValueError("max_iterations is required")
    _check_taped_closures("while_loop", cond, func)
    vars_l = _tolist(loop_vars)
    arity = {}

    def func2(*vs):
        outs, nvs = func(*vs)
        arity["out_single"] = isinstance(outs, NDArray)
        arity["n_out"] = 1 if arity["out_single"] else len(outs)
        return outs, nvs

    flat = invoke_op("while_loop", tuple(vars_l),
                     {"cond": cond, "func": func2,
                      "max_iterations": int(max_iterations)})
    flat = list(flat)
    n_out = arity["n_out"]
    outs = flat[:n_out]
    states = flat[n_out:-1]  # last element is the internal step count
    outs = outs[0] if arity["out_single"] else outs
    if isinstance(loop_vars, NDArray):
        states = states[0]
    elif isinstance(loop_vars, tuple):
        states = tuple(states)
    return outs, states


def cond(pred, then_func, else_func, inputs=None, name=None):
    """parity: nd.contrib.cond.  Branch callables receive *inputs (or no
    arguments, closure-style, when inputs is None — the reference's
    imperative convention)."""
    _check_taped_closures("cond", then_func, else_func)
    inputs_l = _tolist(inputs)
    if inputs is None:
        tf = lambda: then_func()  # noqa: E731
        ef = lambda: else_func()  # noqa: E731
    else:
        tf, ef = then_func, else_func
    arity = {}

    def t2(*a):
        out = tf(*a)
        arity["single"] = isinstance(out, NDArray)
        return out

    def e2(*a):
        out = ef(*a)
        arity["single"] = isinstance(out, NDArray)
        return out

    flat = invoke_op("cond", (pred,) + tuple(inputs_l),
                     {"then_func": t2, "else_func": e2})
    if isinstance(flat, tuple) and arity.get("single"):
        return flat[0]
    if isinstance(flat, tuple):
        return list(flat)
    return flat


def _flat_contrib_ops():
    """Expose every _contrib_-prefixed registry op under nd.contrib too,
    via the same stub factory as the flat nd namespace."""
    from ..base import _OP_REGISTRY
    from . import _make_op_fn

    g = globals()
    for name in list(_OP_REGISTRY):
        if name.startswith("_contrib_"):
            short = name[len("_contrib_"):]
            if short not in g:
                g[short] = _make_op_fn(name)
                __all__.append(short)


_flat_contrib_ops()
