"""NDArray: the imperative tensor (parity: src/ndarray/ndarray.cc +
python/mxnet/ndarray/ndarray.py).

Reference design: NDArray::Chunk = engine variable + Storage handle;
mutation goes through the dependency engine, reads block via WaitToRead.
TPU design: an NDArray is a mutable *slot* holding an immutable jax.Array.
"Mutation" (+=, [:]=, set_data) rebinds the slot to a new functional value —
old buffers stay valid for any recorded autograd residuals, which is exactly
the guarantee the reference's VersionedVarBlock write-serialisation provides,
delivered here for free by value semantics.  Async execution is PJRT's
native dispatch; ``wait_to_read`` = block_until_ready.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as onp

from .. import autograd, engine
from ..base import MXTPUError, get_op
from ..context import Context, current_context

__all__ = ["NDArray", "invoke_op", "array", "waitall"]


_PY_SCALARS = (int, float, bool)


def _place(arr, ctx: Optional[Context]):
    if ctx is None:
        return arr
    dev = ctx.to_jax_device()
    if dev is None:
        return arr
    return jax.device_put(arr, dev)


class NDArray:
    """Imperative tensor wrapping a jax.Array (or tracer, under hybridize).

    Under ``engine.bulk`` an NDArray can be *lazy*: ``_lazy_`` points at
    one output of a pending bulk segment and ``_data_`` is None until the
    segment flushes.  Every read of ``_data`` (the property below) is
    therefore a sync point — asnumpy/item/float()/printing/shape access/
    in-place arithmetic all force the owning segment to compile and run
    before returning a concrete buffer.  Code that never bulks pays one
    attribute check."""

    __slots__ = ("_data_", "_lazy_", "_ctx", "_grad", "_grad_req",
                 "_tape_node", "__weakref__")

    # numpy interop priority (parity: __array_priority__ in reference)
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array) or dtype is not None:
            data = jnp.asarray(data, dtype=jnp.dtype(dtype) if dtype else None)
        self._data = _place(data, ctx)
        self._ctx = ctx
        self._grad = None
        self._grad_req = "null"
        self._tape_node = None

    # -- raw access ------------------------------------------------------
    @property
    def _data(self):
        if self._lazy_ is not None:
            self._force()
        return self._data_

    @_data.setter
    def _data(self, value):
        self._data_ = value
        self._lazy_ = None

    def _force(self):
        """Flush the bulk segment backing this lazy handle (sync point)."""
        lz = self._lazy_
        if lz is not None:
            lz.segment.flush()
            if self._lazy_ is not None:  # defensive: flush must bind us
                self._lazy_ = None
                raise MXTPUError(
                    "bulk segment flush did not materialize this NDArray")

    @property
    def data(self):
        return self._data

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return onp.dtype(str(self._data.dtype)) if not hasattr(
            self._data.dtype, "type") else self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(self._data.size)

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        try:
            # deterministic for sharded arrays: lowest device id
            dev = min(self._data.devices(), key=lambda d: d.id)
            # Context ids are process-LOCAL (multi-process jax assigns
            # global ids like 2048*process_index to local devices); reuse
            # context.py's cached local lists so the two stay consistent
            from ..context import _accel_devices, _devices_for
            locals_ = (_devices_for("cpu") if dev.platform == "cpu"
                       else _accel_devices())
            try:
                local_id = locals_.index(dev)
            except ValueError:
                local_id = dev.id
            if dev.platform == "cpu":
                return Context("cpu", local_id)
            return Context("tpu", local_id)
        except Exception:  # tracers have no device
            return current_context()

    @property
    def is_sharded(self) -> bool:
        """True when the buffer spans multiple devices (SPMD array)."""
        try:
            return len(self._data.devices()) > 1
        except Exception:
            return False

    ctx = context

    @property
    def stype(self):
        return "default"  # sparse storage descoped v1 (SURVEY §7 hard-part 6)

    # -- host transfer ---------------------------------------------------
    def asnumpy(self) -> onp.ndarray:
        return onp.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("the array is not scalar-sized")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of 0-d NDArray")
        return self.shape[0]

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def wait_to_read(self):
        self._data.block_until_ready()

    def wait_to_write(self):
        self._data.block_until_ready()

    # -- autograd --------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        # Parity: attach_grad detaches the array from any recorded graph,
        # making it a fresh autograd leaf.
        self._tape_node = None
        self._grad = NDArray(jnp.zeros(self.shape, self._data.dtype))
        self._grad_req = grad_req

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    def detach(self) -> "NDArray":
        out = NDArray(self._data)
        return out

    def as_np_ndarray(self):
        """The mx.np flavour of this array (shares the buffer AND the
        autograd state, so gradients flow through the conversion; parity:
        NDArray.as_np_ndarray in the 1.6+ reference)."""
        from ..numpy import ndarray as np_ndarray
        return self._as_flavour(np_ndarray)

    def _as_flavour(self, cls):
        out = cls(self._data, ctx=self._ctx)
        out._grad = self._grad
        out._grad_req = self._grad_req
        out._tape_node = self._tape_node
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- placement -------------------------------------------------------
    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return NDArray(self._data, ctx=ctx)

    as_in_ctx = as_in_context

    def copyto(self, other):
        if isinstance(other, Context):
            return NDArray(self._data + 0, ctx=other)
        other._check_inplace_record()
        return other._rebind(_place(self._data + 0, other._ctx))

    def copy(self) -> "NDArray":
        return NDArray(self._data + 0, ctx=self._ctx)

    def astype(self, dtype, copy=True) -> "NDArray":
        return NDArray(self._data.astype(jnp.dtype(dtype)), ctx=self._ctx)

    def tostype(self, stype):
        if stype == "default":
            return self
        if stype == "row_sparse":
            from .sparse import _dense_to_row_sparse
            return _dense_to_row_sparse(self)
        if stype == "csr":
            from .sparse import csr_matrix
            return csr_matrix(self)
        raise MXTPUError(f"unknown storage type {stype!r}")

    # -- mutation --------------------------------------------------------
    def _check_inplace_record(self):
        # Parity: the reference raises when an array in the autograd graph
        # is mutated while recording (would corrupt the gradient graph).
        if autograd.is_recording() and autograd._on_tape(self):
            raise MXTPUError(
                "in-place mutation of an NDArray that is part of the "
                "recorded autograd graph is not allowed inside "
                "autograd.record(); use functional ops instead")

    def _rebind(self, new_data):
        """In-place semantic: swap the buffer in the slot."""
        self._data = new_data
        if engine.is_sync():
            try:
                new_data.block_until_ready()
            except AttributeError:
                pass
        return self

    def _rebind_from(self, other: "NDArray"):
        """Adopt ``other``'s buffer, lazily when possible: a pending bulk
        result transfers to this slot without forcing a flush (the fused
        trainer update path stays lazy end-to-end).  Not for use inside
        autograd.record() — tape identity stays with ``other``."""
        lz = other._lazy_
        if lz is not None:
            try:
                lz.segment.add_ref(lz.node, lz.out, self)
            except engine._SegmentClosed:
                return self._rebind(other._data)
            self._data_ = None
            self._lazy_ = lz
            return self
        return self._rebind(other._data_)

    def __setitem__(self, key, value):
        self._check_inplace_record()
        key = _translate_index(key)
        if isinstance(value, NDArray):
            value = value._data
        self._rebind(self._data.at[key].set(value))

    def __getitem__(self, key):
        # routed through the op registry so the autograd tape records the
        # gather (a bare self._data[key] would silently break the chain)
        key = _translate_index(key)
        return invoke_op("_internal_getitem", (self,), {"key": key})

    # -- shape ops (method forms) ---------------------------------------
    def reshape(self, *shape, **kwargs):
        if not shape and "shape" in kwargs:
            shape = tuple(kwargs.pop("shape"))
        elif len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return invoke_op("reshape", (self,), {"shape": shape})

    def reshape_like(self, other):
        return invoke_op("reshape_like", (self, other), {})

    def flatten(self):
        return invoke_op("flatten", (self,), {})

    def expand_dims(self, axis):
        return invoke_op("expand_dims", (self,), {"axis": axis})

    def squeeze(self, axis=None):
        return invoke_op("squeeze", (self,), {"axis": axis})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return invoke_op("transpose", (self,), {"axes": axes or None})

    @property
    def T(self):
        return invoke_op("transpose", (self,), {"axes": None})

    def swapaxes(self, dim1, dim2):
        return invoke_op("swapaxes", (self,), {"dim1": dim1, "dim2": dim2})

    def broadcast_to(self, shape):
        return invoke_op("broadcast_to", (self,), {"shape": shape})

    def broadcast_like(self, other):
        return invoke_op("broadcast_like", (self, other), {})

    def tile(self, reps):
        return invoke_op("tile", (self,), {"reps": reps})

    def repeat(self, repeats, axis=None):
        return invoke_op("repeat", (self,), {"repeats": repeats, "axis": axis})

    def flip(self, axis):
        return invoke_op("flip", (self,), {"axis": axis})

    def slice_axis(self, axis, begin, end):
        return invoke_op("slice_axis", (self,),
                         {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return invoke_op("take", (self, indices), {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kw):
        return invoke_op("one_hot", (self,), dict(depth=depth, **kw))

    # -- reductions ------------------------------------------------------
    def sum(self, axis=None, keepdims=False):
        return invoke_op("sum", (self,), {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return invoke_op("mean", (self,), {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return invoke_op("max", (self,), {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return invoke_op("min", (self,), {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return invoke_op("prod", (self,), {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke_op("norm", (self,),
                         {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return invoke_op("argmax", (self,), {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return invoke_op("argmin", (self,), {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return invoke_op("argsort", (self,), {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, **kw):
        return invoke_op("topk", (self,), dict(axis=axis, k=k, **kw))

    # -- elementwise method forms ---------------------------------------
    def abs(self):
        return invoke_op("abs", (self,), {})

    def sqrt(self):
        return invoke_op("sqrt", (self,), {})

    def square(self):
        return invoke_op("square", (self,), {})

    def exp(self):
        return invoke_op("exp", (self,), {})

    def log(self):
        return invoke_op("log", (self,), {})

    def relu(self):
        return invoke_op("relu", (self,), {})

    def sigmoid(self):
        return invoke_op("sigmoid", (self,), {})

    def tanh(self):
        return invoke_op("tanh", (self,), {})

    def clip(self, a_min=None, a_max=None):
        return invoke_op("clip", (self,), {"a_min": a_min, "a_max": a_max})

    def round(self):
        return invoke_op("round", (self,), {})

    def sign(self):
        return invoke_op("sign", (self,), {})

    def softmax(self, axis=-1):
        return invoke_op("softmax", (self,), {"axis": axis})

    def log_softmax(self, axis=-1):
        return invoke_op("log_softmax", (self,), {"axis": axis})

    def dot(self, other, **kw):
        return invoke_op("dot", (self, other), kw)

    def zeros_like(self):
        return invoke_op("zeros_like", (self,), {})

    def ones_like(self):
        return invoke_op("ones_like", (self,), {})

    # -- arithmetic dunders ---------------------------------------------
    def __add__(self, other):
        return invoke_op("add", (self, other), {})

    def __radd__(self, other):
        return invoke_op("add", (other, self), {})

    def __sub__(self, other):
        return invoke_op("subtract", (self, other), {})

    def __rsub__(self, other):
        return invoke_op("subtract", (other, self), {})

    def __mul__(self, other):
        return invoke_op("multiply", (self, other), {})

    def __rmul__(self, other):
        return invoke_op("multiply", (other, self), {})

    def __truediv__(self, other):
        return invoke_op("divide", (self, other), {})

    def __rtruediv__(self, other):
        return invoke_op("divide", (other, self), {})

    def __mod__(self, other):
        return invoke_op("mod", (self, other), {})

    def __rmod__(self, other):
        return invoke_op("mod", (other, self), {})

    def __pow__(self, other):
        return invoke_op("power", (self, other), {})

    def __rpow__(self, other):
        return invoke_op("power", (other, self), {})

    def __neg__(self):
        return invoke_op("negative", (self,), {})

    def __abs__(self):
        return invoke_op("abs", (self,), {})

    def __matmul__(self, other):
        return invoke_op("dot", (self, other), {})

    def __iadd__(self, other):
        self._check_inplace_record()
        o = other._data if isinstance(other, NDArray) else other
        return self._rebind(self._data + o)

    def __isub__(self, other):
        self._check_inplace_record()
        o = other._data if isinstance(other, NDArray) else other
        return self._rebind(self._data - o)

    def __imul__(self, other):
        self._check_inplace_record()
        o = other._data if isinstance(other, NDArray) else other
        return self._rebind(self._data * o)

    def __itruediv__(self, other):
        self._check_inplace_record()
        o = other._data if isinstance(other, NDArray) else other
        return self._rebind(self._data / o)

    def __eq__(self, other):
        if other is None:
            return False
        return invoke_op("equal", (self, other), {})

    def __ne__(self, other):
        if other is None:
            return True
        return invoke_op("not_equal", (self, other), {})

    def __gt__(self, other):
        return invoke_op("greater", (self, other), {})

    def __ge__(self, other):
        return invoke_op("greater_equal", (self, other), {})

    def __lt__(self, other):
        return invoke_op("lesser", (self, other), {})

    def __le__(self, other):
        return invoke_op("lesser_equal", (self, other), {})

    def __hash__(self):
        return id(self)

    def __repr__(self):
        try:
            arr = self.asnumpy()
            return f"{arr}\n<NDArray {self.shape} @{self.context}>"
        except Exception:
            return f"<NDArray {self.shape} {self._data.dtype} (traced)>"

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


def _translate_index(key):
    if isinstance(key, NDArray):
        return key._data
    if isinstance(key, tuple):
        return tuple(k._data if isinstance(k, NDArray) else k for k in key)
    return key


def _wrap_result(res, ctx, cls=None):
    """Wrap raw jax results; `cls` propagates NDArray subclasses (mx.np
    ndarray results stay np ndarrays through every registry op)."""
    cls = cls or NDArray
    if isinstance(res, (tuple, list)):
        return tuple(cls(r, ctx=ctx) for r in res)
    return cls(res, ctx=ctx)


try:
    from jax.core import Tracer as _Tracer
except ImportError:  # pragma: no cover - jax layout drift
    from jax._src.core import Tracer as _Tracer

# sentinel: "this op was not bulked, dispatch it normally"
_NOT_BULKED = object()


def _new_lazy_handle(cls, lazyref):
    """A lazy NDArray bound to one pending bulk-segment output.  Bypasses
    __init__ (there is no buffer yet); both NDArray flavours are
    slots+methods only, so direct slot initialization is complete."""
    h = cls.__new__(cls)
    h._data_ = None
    h._lazy_ = lazyref
    h._ctx = None
    h._grad = None
    h._grad_req = "null"
    h._tape_node = None
    return h


def _bulk_record(seg, name: str, spec, args: tuple, kwargs: dict):
    """Append one eager op to the open bulk segment and return lazy
    handles, or _NOT_BULKED when the op must dispatch per-op (out=/ctx=
    requested, tracer inputs, unfreezable statics, ...).  Fallthrough
    needs no explicit flush: a fallthrough op reading a lazy input forces
    the segment through the ``_data`` property."""
    if kwargs.get("out") is not None or kwargs.get("ctx") is not None:
        engine._STATS["fallthroughs"] += 1
        return _NOT_BULKED

    n_outs = spec.num_outputs
    if callable(n_outs):
        try:
            n_outs = int(n_outs({k: v for k, v in kwargs.items()
                                 if not isinstance(v, NDArray)}))
        except Exception:
            engine._STATS["fallthroughs"] += 1
            return _NOT_BULKED
        if n_outs == 1:
            # a declared-arity op returning a 1-tuple is indistinguishable
            # from a bare-array op post-hoc; keep per-op dispatch for the
            # tuple-shaped return
            engine._STATS["fallthroughs"] += 1
            return _NOT_BULKED
    elif n_outs is None:
        # registry invariant (audit rule R002): an op that declares no
        # num_outputs returns exactly one array
        n_outs = 1

    recording = autograd.is_recording()
    kwargs = dict(kwargs)
    # explicit out=None / ctx=None are dispatch directives, not op
    # params — strip them exactly like the per-op path's pops (leaving
    # them would hand the op fn an unexpected kwarg inside the trace)
    kwargs.pop("out", None)
    kwargs.pop("ctx", None)
    # resolve runtime-state injection at RECORD time: the train flag is
    # the record-time truth, and the RNG key stream is consumed in
    # program order exactly as per-op dispatch would (bit-exact seeded
    # runs).  The key itself is drawn only AFTER every bulkability check
    # passes — a fallthrough op must not burn a key the normal dispatch
    # path will draw again.
    rng_wanted = _RNG_GATE.get(name, _ALWAYS)(kwargs)
    if name in _NEEDS_TRAIN_FLAG and rng_wanted:
        kwargs.setdefault("_training", autograd.is_training())
    need_key = (name in _NEEDS_KEY and rng_wanted
                and kwargs.get("_key") is None
                and (kwargs.get("_training")
                     or kwargs.get("mode") == "always"))

    # pre-force foreign lazies OUTSIDE our segment lock (taking another
    # segment's lock while holding ours could deadlock against a thread
    # doing the reverse)
    for a in args:
        if isinstance(a, NDArray):
            lz = a._lazy_
            if lz is not None and lz.segment is not seg:
                a._force()
    for v in kwargs.values():
        if isinstance(v, NDArray):
            lz = v._lazy_
            if lz is not None and lz.segment is not seg:
                v._force()

    run_args, sig_args = [], []
    res_cls = NDArray
    node_on_tape = False
    tape_inputs = []   # ext input indices whose source NDArray is on tape
    n_inputs0 = None
    try:
        # the whole record commits atomically against a cross-thread
        # flush: ops must not land in a flushed segment (they would
        # never run), and flush's snapshot must not tear mid-append
        with seg._lock:
            if seg.closed:
                raise engine._SegmentClosed
            n_inputs0 = len(seg.inputs)
            for a in args:
                if isinstance(a, NDArray):
                    if type(a) is not NDArray and res_cls is NDArray:
                        res_cls = type(a)
                    lz = a._lazy_
                    if lz is not None and lz.segment is seg:
                        run_args.append(("r", lz.node, lz.out))
                        sig_args.append(("r", lz.node, lz.out))
                        node_on_tape |= (recording
                                         and a._tape_node is not None)
                        continue
                    if lz is not None:
                        # a foreign lazy raced in after the pre-pass:
                        # bail, the per-op path forces it lock-free
                        raise engine._SegmentClosed
                    d = a._data_
                    if isinstance(d, _Tracer):
                        raise engine._Unfreezable("tracer input")
                    on_tape = recording and autograd._on_tape(a)
                    idx = seg.add_input(d, a, on_tape)
                    run_args.append(("x", idx))
                    sig_args.append(("x", idx))
                    if on_tape:
                        tape_inputs.append(idx)
                    node_on_tape |= on_tape
                elif isinstance(a, _Tracer):
                    raise engine._Unfreezable("tracer input")
                elif isinstance(a, jax.Array):
                    idx = seg.add_input(a, None, False)
                    run_args.append(("x", idx))
                    sig_args.append(("x", idx))
                else:
                    run_args.append(("c", a))
                    sig_args.append(("c", engine._freeze_static(a)))

            kw_run, kw_sig, statics, statics_sig = [], [], {}, []
            for k, v in kwargs.items():
                if isinstance(v, NDArray):
                    lz = v._lazy_
                    if lz is not None and lz.segment is seg:
                        kw_run.append((k, ("r", lz.node, lz.out)))
                        kw_sig.append((k, ("r", lz.node, lz.out)))
                        continue
                    if lz is not None:
                        raise engine._SegmentClosed
                    d = v._data_
                    if isinstance(d, _Tracer):
                        raise engine._Unfreezable("tracer input")
                    idx = seg.add_input(d, None, False)
                    kw_run.append((k, ("x", idx)))
                    kw_sig.append((k, ("x", idx)))
                elif isinstance(v, _Tracer):
                    raise engine._Unfreezable("tracer input")
                elif isinstance(v, jax.Array):
                    idx = seg.add_input(v, None, False)
                    kw_run.append((k, ("x", idx)))
                    kw_sig.append((k, ("x", idx)))
                else:
                    statics[k] = v
                    statics_sig.append((k, engine._freeze_static(v)))

            if need_key:
                # all checks passed — the op IS bulked — so consuming
                # the key here cannot double-draw with a fallthrough
                from .. import random as _rnd
                idx = seg.add_input(_rnd.next_key(), None, False)
                kw_run.append(("_key", ("x", idx)))
                kw_sig.append(("_key", ("x", idx)))

            eligible = recording and spec.differentiable and node_on_tape
            if eligible:
                seg.mark_diff_inputs(tape_inputs)
            node_sig = (name, tuple(sig_args), tuple(sorted(kw_sig)),
                        tuple(sorted(statics_sig)), n_outs, eligible)
            prog = engine._NodeProg(spec.fn, name, run_args, kw_run,
                                    statics, n_outs, eligible, node_sig)
            node_idx = seg.add_node(prog)

            handles = []
            for j in range(n_outs):
                h = _new_lazy_handle(
                    res_cls, engine._LazyRef(seg, node_idx, j))
                if eligible:
                    h._tape_node = engine.PENDING_TAPE
                seg.add_ref(node_idx, j, h)
                handles.append(h)
    except (engine._Unfreezable, engine._SegmentClosed):
        if n_inputs0 is not None:
            # drop inputs this aborted record appended — orphans would
            # pollute the segment's cache signature and vjp primal set
            seg.rollback_inputs(n_inputs0)
        engine._STATS["fallthroughs"] += 1
        return _NOT_BULKED

    if seg.full:
        engine.flush_bulk()
    return handles[0] if n_outs == 1 else tuple(handles)


def invoke_op(name: str, args: tuple, kwargs: dict):
    """The imperative dispatch path (parity: MXImperativeInvokeEx →
    Imperative::Invoke → PushFCompute → Engine::PushAsync; see SURVEY.md
    §3.1).  Here: unwrap → jax op (PJRT async dispatch) → wrap; when the
    autograd tape is recording, compute through jax.vjp and record a
    TapeNode (parity: Imperative::RecordOp).

    Under ``engine.bulk`` the op is not dispatched: it records into the
    thread's BulkSegment and returns lazy handles (see engine.py) —
    genuine op bulking, compiled once per segment signature.
    """
    spec = get_op(name)

    seg = engine.current_segment()
    if seg is not None and spec.bulkable and not _OUTPUT_MONITORS:
        res = _bulk_record(seg, name, spec, args, kwargs)
        if res is not _NOT_BULKED:
            return res

    out = kwargs.pop("out", None)
    ctx = kwargs.pop("ctx", None)

    nd_args = []
    raw_args = []
    for a in args:
        if isinstance(a, NDArray):
            nd_args.append(a)
            raw_args.append(a._data)
        else:
            raw_args.append(a)
    # array-valued keyword params (e.g. sequence_length) are non-diff inputs
    kwargs = {k: (v._data if isinstance(v, NDArray) else v)
              for k, v in kwargs.items()}

    recording = (autograd.is_recording() and spec.differentiable
                 and any(autograd._on_tape(a) for a in nd_args))
    # result class follows the inputs: mx.np ndarrays beget mx.np ndarrays;
    # any subclass operand wins regardless of operand order
    res_cls = next((type(a) for a in nd_args if type(a) is not NDArray),
                   NDArray)

    # inject runtime-state kwargs some ops need.  _RNG_GATE ops consume
    # RNG conditionally (switch_moe: only when router_jitter > 0) —
    # gating the injection keeps the global key stream, and so seeded
    # reproducibility of jitter-free MoE runs, identical to a model
    # without MoE layers.  The gated params are keyword-only in the op
    # signatures, so kwargs is the complete truth here.
    fn = spec.fn
    rng_wanted = _RNG_GATE.get(name, _ALWAYS)(kwargs)
    if name in _NEEDS_TRAIN_FLAG and rng_wanted:
        kwargs.setdefault("_training", autograd.is_training())
    if name in _NEEDS_KEY and rng_wanted:
        from .. import random as _rnd
        if kwargs.get("_key") is None and (
                kwargs.get("_training") or kwargs.get("mode") == "always"):
            kwargs["_key"] = _rnd.next_key()

    if recording:
        # differentiate wrt the NDArray positional args only
        diff_idx = [i for i, a in enumerate(args) if isinstance(a, NDArray)]

        def f(*diff_arrays):
            call = list(raw_args)
            for i, arr in zip(diff_idx, diff_arrays):
                call[i] = arr
            return fn(*call, **kwargs)

        primals = tuple(a._data for a in nd_args)
        res, vjp_fn = jax.vjp(f, *primals)
        outs = _wrap_result(res, ctx, res_cls)
        out_list = list(outs) if isinstance(outs, tuple) else [outs]
        autograd.record_node(vjp_fn, nd_args, out_list, name)
    else:
        res = fn(*raw_args, **kwargs)
        outs = _wrap_result(res, ctx, res_cls)
        out_list = list(outs) if isinstance(outs, tuple) else [outs]

    if engine.is_sync():
        for o in out_list:
            try:
                o._data.block_until_ready()
            except AttributeError:
                pass  # tracer

    if _OUTPUT_MONITORS:
        for cb in list(_OUTPUT_MONITORS):
            for o in out_list:
                cb(name, o)

    if out is not None:
        if isinstance(outs, tuple):
            raise MXTPUError("out= with multi-output op unsupported")
        if recording:
            raise MXTPUError(
                "out= is not supported inside autograd.record() (the tape "
                "tracks functional outputs only; parity with reference)")
        out._rebind(outs._data)
        return out
    return outs


# ops whose behavior depends on autograd train/predict mode or RNG
_NEEDS_TRAIN_FLAG = {"Dropout", "dropout", "BatchNorm", "batch_norm",
                     "RNN", "rnn", "switch_moe"}
_NEEDS_KEY = {"Dropout", "dropout", "RNN", "rnn", "switch_moe"}
_ALWAYS = lambda kw: True  # noqa: E731
# per-op predicate deciding whether the RNG state kwargs get injected
_RNG_GATE = {"switch_moe": lambda kw: bool(kw.get("router_jitter"))}

# op-output taps installed by mx.monitor.Monitor (parity: executor monitor
# callback — the reference taps op outputs in the engine)
_OUTPUT_MONITORS: list = []


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Parity: mx.nd.array."""
    if isinstance(source, NDArray):
        # always a copy (parity: mx.nd.array never aliases its source)
        data = source._data.astype(jnp.dtype(dtype)) if dtype else (
            source._data + 0)
        return NDArray(data, ctx=ctx)
    keep_dtype = isinstance(source, onp.ndarray) or hasattr(source, "dtype")
    a = onp.asarray(source, dtype=dtype)
    if dtype is None and not keep_dtype:
        a = a.astype(onp.float32)  # MXNet default dtype for python lists
    elif dtype is None and a.dtype == onp.float64:
        a = a.astype(onp.float32)
    return NDArray(jnp.asarray(a), ctx=ctx)


def waitall():
    engine.wait_all()
