"""mx.nd.random namespace (parity: python/mxnet/ndarray/random.py,
src/operator/random/sample_op.cc).  Draws flow from the global key-ring in
mxtpu/random.py, so ``mx.random.seed`` makes them reproducible."""

from __future__ import annotations

from .. import random as _rnd
from .ndarray import NDArray

__all__ = ["uniform", "normal", "randn", "randint", "exponential", "gamma",
           "poisson", "multinomial", "shuffle", "bernoulli"]


def _wrap(raw, ctx):
    out = NDArray(raw, ctx=ctx)
    return out


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None):
    res = _wrap(_rnd.uniform(low, high, shape, dtype), ctx)
    if out is not None:
        out._rebind(res._data)
        return out
    return res


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    res = _wrap(_rnd.normal(loc, scale, shape, dtype), ctx)
    if out is not None:
        out._rebind(res._data)
        return out
    return res


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return _wrap(_rnd.normal(loc, scale, shape, dtype), ctx)


def randint(low=0, high=None, shape=None, dtype="int32", ctx=None, out=None):
    res = _wrap(_rnd.randint(low, high, shape, dtype), ctx)
    if out is not None:
        out._rebind(res._data)
        return out
    return res


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None):
    return _wrap(_rnd.exponential(scale, shape, dtype), ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None):
    return _wrap(_rnd.gamma(alpha, beta, shape, dtype), ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None):
    return _wrap(_rnd.poisson(lam, shape, dtype), ctx)


def multinomial(data, shape=None, get_prob=False, dtype="int32"):
    res = _rnd.multinomial(data.data, shape, get_prob, dtype)
    if get_prob:
        return _wrap(res[0], None), _wrap(res[1], None)
    return _wrap(res, None)


def shuffle(data):
    return _wrap(_rnd.shuffle(data.data), None)


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None):
    import jax

    k = _rnd.next_key()
    return _wrap(
        jax.random.bernoulli(k, prob, _rnd._shape(shape)).astype(dtype), ctx)
