"""Sparse NDArray storage (parity: python/mxnet/ndarray/sparse.py over
src/ndarray/ndarray.cc kRowSparseStorage/kCSRStorage).

TPU-native scope: XLA kernels are dense — the reference's motivation for
row_sparse (skip untouched embedding rows in the optimizer update and on
the wire) is served here by keeping COMPUTE dense under jit (XLA
scatter-add is the fast path on TPU) while representing STORAGE and
COMMUNICATION sparsely: RowSparseNDArray carries (indices, values) for
gradients/pulls whose touched-row set is known (Embedding sparse_grad,
kvstore row_sparse_pull), and the SGD update applies only those rows.
CSRNDArray is the minimal read-side format (todense + dot).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as onp

from ..base import MXTPUError
from .ndarray import NDArray

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array",
           "csr_matrix", "array", "zeros"]


class BaseSparseNDArray:
    stype = "undefined"

    # shared face with NDArray so metric/trainer code can stay generic
    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    ctx = context

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= s
        return n

    def wait_to_read(self):
        self.data.wait_to_read()

    def asnumpy(self):
        return self.todense().asnumpy()

    def __repr__(self):
        return "<%s %s @%s>" % (type(self).__name__, self.shape, self.stype)


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values) rows of a dense 2-D+ array (parity:
    RowSparseNDArray).  indices: (nnz,) int32 sorted row ids; values:
    (nnz, *row_shape)."""

    stype = "row_sparse"

    def __init__(self, values, indices, shape):
        values = values if isinstance(values, NDArray) else NDArray(values)
        indices = indices if isinstance(indices, NDArray) else \
            NDArray(indices, dtype="int32")
        if indices.ndim != 1:
            raise MXTPUError("row_sparse indices must be 1-D row ids")
        if values.shape[0] != indices.shape[0]:
            raise MXTPUError("values/indices leading dims differ")
        if tuple(values.shape[1:]) != tuple(shape[1:]):
            raise MXTPUError("values row shape %s != dense row shape %s"
                             % (values.shape[1:], shape[1:]))
        self._values = values
        self._indices = indices
        self._shape = tuple(shape)

    # -- reference surface ----------------------------------------------
    @property
    def data(self) -> NDArray:
        return self._values

    @property
    def indices(self) -> NDArray:
        return self._indices

    @property
    def shape(self):
        return self._shape

    def todense(self) -> NDArray:
        dense = jnp.zeros(self._shape, self._values.data.dtype)
        dense = dense.at[self._indices.data].add(self._values.data)
        return NDArray(dense)

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == "row_sparse":
            return self
        raise MXTPUError(f"cannot convert row_sparse to {stype!r}")

    def retain(self, row_ids) -> "RowSparseNDArray":
        """Keep only the requested rows (parity: sparse.retain)."""
        row_ids = row_ids if isinstance(row_ids, NDArray) else \
            NDArray(row_ids, dtype="int32")
        ids = onp.asarray(row_ids.data).astype("int64")
        have = onp.asarray(self._indices.data).astype("int64")
        pos = {int(r): i for i, r in enumerate(have)}
        keep = [r for r in ids if int(r) in pos]
        sel = jnp.asarray([pos[int(r)] for r in keep], jnp.int32)
        vals = jnp.take(self._values.data, sel, axis=0) if keep else \
            jnp.zeros((0,) + self._shape[1:], self._values.data.dtype)
        return RowSparseNDArray(NDArray(vals),
                                NDArray(jnp.asarray(keep, jnp.int32)),
                                self._shape)

    def copy(self):
        return RowSparseNDArray(self._values.copy(), self._indices.copy(),
                                self._shape)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other._values = self._values.copy()
            other._indices = self._indices.copy()
            other._shape = self._shape
            return other
        return self.todense().copyto(other)

    def astype(self, dtype):
        return RowSparseNDArray(self._values.astype(dtype), self._indices,
                                self._shape)

    def as_in_context(self, ctx):
        return RowSparseNDArray(self._values.as_in_context(ctx),
                                self._indices.as_in_context(ctx),
                                self._shape)


class CSRNDArray(BaseSparseNDArray):
    """Compressed-sparse-row 2-D array (parity: CSRNDArray; read-side
    minimal: construct, todense, dot-with-dense via densify)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self._data = data if isinstance(data, NDArray) else NDArray(data)
        self._indices = indices if isinstance(indices, NDArray) else \
            NDArray(indices, dtype="int32")
        self._indptr = indptr if isinstance(indptr, NDArray) else \
            NDArray(indptr, dtype="int32")
        self._shape = tuple(shape)

    @property
    def data(self):
        return self._data

    @property
    def indices(self):
        return self._indices

    @property
    def indptr(self):
        return self._indptr

    @property
    def shape(self):
        return self._shape

    def todense(self) -> NDArray:
        n_rows = self._shape[0]
        indptr = onp.asarray(self._indptr.data)
        rows = onp.repeat(onp.arange(n_rows), onp.diff(indptr))
        dense = jnp.zeros(self._shape, self._data.data.dtype)
        dense = dense.at[jnp.asarray(rows),
                         self._indices.data].add(self._data.data)
        return NDArray(dense)

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == "csr":
            return self
        raise MXTPUError(f"cannot convert csr to {stype!r}")


# -- constructors ------------------------------------------------------------

def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """(data, indices) tuple, dense array, or RowSparseNDArray →
    RowSparseNDArray (parity: sparse.row_sparse_array)."""
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else NDArray(
            data, dtype=dtype)
        if shape is None:
            raise MXTPUError("shape is required for (data, indices) input")
        return RowSparseNDArray(data, indices, shape)
    dense = arg1 if isinstance(arg1, NDArray) else NDArray(arg1, dtype=dtype)
    return _dense_to_row_sparse(dense)


def _dense_to_row_sparse(dense: NDArray) -> RowSparseNDArray:
    arr = onp.asarray(dense.data)
    nz = onp.nonzero(arr.reshape(arr.shape[0], -1).any(axis=1))[0]
    vals = jnp.take(dense.data, jnp.asarray(nz, jnp.int32), axis=0)
    return RowSparseNDArray(NDArray(vals),
                            NDArray(jnp.asarray(nz, jnp.int32)),
                            dense.shape)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """(data, indices, indptr) tuple or dense → CSRNDArray."""
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXTPUError("shape is required for (data,indices,indptr)")
        return CSRNDArray(data, indices, indptr, shape)
    dense = arg1 if isinstance(arg1, NDArray) else NDArray(arg1, dtype=dtype)
    arr = onp.asarray(dense.data)
    if arr.ndim != 2:
        raise MXTPUError("csr_matrix requires a 2-D input")
    indptr = [0]
    cols = []
    vals = []
    for row in arr:
        nz = onp.nonzero(row)[0]
        cols.extend(nz.tolist())
        vals.extend(row[nz].tolist())
        indptr.append(len(cols))
    return CSRNDArray(NDArray(onp.asarray(vals, arr.dtype)),
                      NDArray(onp.asarray(cols, "int32")),
                      NDArray(onp.asarray(indptr, "int32")),
                      dense.shape)


def array(source_array, ctx=None, dtype=None):
    """parity: mx.nd.sparse.array — passthrough constructor."""
    if isinstance(source_array, (RowSparseNDArray, CSRNDArray)):
        return source_array
    raise MXTPUError("use row_sparse_array/csr_matrix for dense input "
                     "(stype is ambiguous)")


def zeros(stype, shape, ctx=None, dtype="float32"):
    if stype == "row_sparse":
        return RowSparseNDArray(
            NDArray(jnp.zeros((0,) + tuple(shape[1:]), jnp.dtype(dtype))),
            NDArray(jnp.zeros((0,), jnp.int32)), shape)
    if stype == "csr":
        return CSRNDArray(NDArray(jnp.zeros((0,), jnp.dtype(dtype))),
                          NDArray(jnp.zeros((0,), jnp.int32)),
                          NDArray(jnp.zeros((shape[0] + 1,), jnp.int32)),
                          shape)
    raise MXTPUError(f"unknown sparse stype {stype!r}")
