"""The generated ``mx.nd`` namespace.

Parity: python/mxnet/ndarray/register.py _init_ndarray_module — the
reference synthesises Python functions for every op in the C registry at
import time; we do the same from the mxtpu registry (populated by importing
mxtpu.ops).  ``mx.nd.<op>(*ndarrays, **params)`` for every registered op.
"""

from __future__ import annotations

import sys as _sys

from .. import ops as _ops  # populates the registry  # noqa: F401
from ..base import _OP_REGISTRY
from .ndarray import NDArray, array, invoke_op, waitall
from . import random  # noqa: F401
from .serialization import save, load  # noqa: F401

__all__ = ["NDArray", "array", "waitall", "save", "load", "random"]


def _make_op_fn(name):
    def op_fn(*args, **kwargs):
        return invoke_op(name, args, kwargs)

    op_fn.__name__ = name
    spec = _OP_REGISTRY[name]
    op_fn.__doc__ = spec.fn.__doc__ or f"Generated op {name!r} (jax-backed)."
    return op_fn


_mod = _sys.modules[__name__]
for _name in list(_OP_REGISTRY):
    if not hasattr(_mod, _name):
        setattr(_mod, _name, _make_op_fn(_name))
        __all__.append(_name)

# after _make_op_fn exists (contrib reuses it for its flat op stubs)
from . import contrib  # noqa: F401,E402
from . import sparse  # noqa: F401,E402


# legacy flat random-op names (mx.nd.random_uniform etc.)
def random_uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None):
    return random.uniform(low, high, shape, dtype, ctx)


def random_normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None):
    return random.normal(loc, scale, shape, dtype, ctx)


def sample_multinomial(data, shape=None, get_prob=False, dtype="int32"):
    return random.multinomial(data, shape, get_prob, dtype)


def empty(shape, ctx=None, dtype="float32"):
    """Parity: mx.nd.empty (deferred-alloc in reference; zeros here — XLA
    has no uninitialised buffers)."""
    return invoke_op("zeros", (), {"shape": shape, "dtype": dtype, "ctx": ctx})


def moveaxis(a, source, destination):
    import jax.numpy as jnp

    return NDArray(jnp.moveaxis(a.data, source, destination))


def concatenate(arrays, axis=0):
    return invoke_op("concat", tuple(arrays), {"dim": axis})


def add_n(*arrays):
    out = arrays[0]
    for a in arrays[1:]:
        out = out + a
    return out


ElementWiseSum = add_n
