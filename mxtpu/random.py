"""RNG state (parity: python/mxnet/random.py + src/resource.cc kRandom).

The reference keeps stateful per-device Philox/MT generators owned by the
ResourceManager; ops draw from them imperatively.  JAX is functional: all
randomness flows from explicit keys.  We bridge the two with a global
key-ring: ``mx.random.seed(s)`` resets it, each random draw folds a counter
into the root key.  Under a jit trace (hybridize / make_train_step) the
active trace pushes a _TraceKeyCtx so that the *traced* key is threaded in
as an argument — compiled steps get fresh randomness per call without
retracing (the TPU answer to cuDNN dropout states).

Numeric parity with the reference's Philox streams is impossible and not a
goal (SURVEY.md §7 hard-part 5): API parity + statistical behavior only.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as onp

__all__ = ["seed", "uniform", "normal", "randint", "randn", "shuffle",
           "multinomial", "gamma", "exponential", "poisson",
           "generator", "next_key", "get_state", "set_state"]


class _KeyRing:
    """Root key is created lazily so `import mxtpu` never initialises the
    JAX backend (the context module makes the same promise)."""

    def __init__(self, s: int = 0):
        self._seed = s
        self._root = None
        self._counter = 0

    def seed(self, s: int):
        self._seed = s
        self._root = None
        self._counter = 0

    def next_key(self):
        if self._root is None:
            self._root = jax.random.key(self._seed)
        k = jax.random.fold_in(self._root, self._counter)
        self._counter += 1
        return k

    def peek_key(self, ahead=0):
        """The key ``ahead`` draws in the future WITHOUT consuming it —
        key_i is a pure function of (root, counter), so speculative
        verification can compute candidate draws for a whole window and
        afterwards :meth:`advance` by only the number of tokens actually
        emitted, leaving the stream bit-identical to having drawn them
        one by one (mxtpu.parallel.serving speculative decode)."""
        if self._root is None:
            self._root = jax.random.key(self._seed)
        return jax.random.fold_in(self._root, self._counter + int(ahead))

    def advance(self, n):
        """Consume ``n`` draws (the commit half of peek_key)."""
        self._counter += int(n)


class _TraceKeyCtx:
    """Deterministic per-trace key derivation; pushed while tracing."""

    def __init__(self, key):
        self.key = key
        self.n = 0

    def next_key(self):
        k = jax.random.fold_in(self.key, self.n)
        self.n += 1
        return k


_GLOBAL = _KeyRing(int(onp.random.randint(0, 2**31 - 1)))
_TRACE_STACK: List[_TraceKeyCtx] = []


def generator() -> _KeyRing:
    return _GLOBAL


def push_trace_key(key) -> _TraceKeyCtx:
    ctx = _TraceKeyCtx(key)
    _TRACE_STACK.append(ctx)
    return ctx


def pop_trace_key():
    _TRACE_STACK.pop()


def in_trace() -> bool:
    return bool(_TRACE_STACK)


def next_key():
    if _TRACE_STACK:
        return _TRACE_STACK[-1].next_key()
    return _GLOBAL.next_key()


def get_state():
    """(seed, draw_counter) of the global key-ring — everything needed to
    reproduce the stream from here.  Checkpoint/rollback support
    (resilience.guardian): saving this at a step boundary and restoring
    it makes the replayed key stream bit-identical."""
    return (_GLOBAL._seed, _GLOBAL._counter)


def set_state(state):
    """Restore a (seed, draw_counter) snapshot from :func:`get_state`."""
    s, counter = state
    _GLOBAL._seed = int(s)
    _GLOBAL._root = None  # re-derived lazily from the restored seed
    _GLOBAL._counter = int(counter)


def seed(seed_state: int, ctx: str = "all"):
    """Parity: mx.random.seed.  ctx arg accepted and ignored (single key-ring
    drives all devices; per-device streams come from fold_in of device id
    inside sharded computations)."""
    _GLOBAL.seed(int(seed_state))
    onp.random.seed(int(seed_state) % (2**32))


# -- raw draws returning jax arrays (the nd/gluon layers wrap these) --------

def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else next_key()
    return jax.random.uniform(k, _shape(shape), dtype=jnp.dtype(dtype),
                              minval=low, maxval=high)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else next_key()
    return loc + scale * jax.random.normal(k, _shape(shape), dtype=jnp.dtype(dtype))


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", key=None):
    return normal(loc, scale, shape, dtype, key)


def randint(low=0, high=None, shape=None, dtype="int32", key=None):
    k = key if key is not None else next_key()
    return jax.random.randint(k, _shape(shape), low, high, dtype=jnp.dtype(dtype))


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else next_key()
    return jax.random.gamma(k, alpha, _shape(shape), dtype=jnp.dtype(dtype)) * beta


def exponential(scale=1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else next_key()
    return jax.random.exponential(k, _shape(shape), dtype=jnp.dtype(dtype)) * scale


def poisson(lam=1.0, shape=None, dtype="float32", key=None):
    k = key if key is not None else next_key()
    return jax.random.poisson(k, lam, _shape(shape)).astype(jnp.dtype(dtype))


def shuffle(data, key=None):
    k = key if key is not None else next_key()
    return jax.random.permutation(k, data, axis=0)


def multinomial(data, shape=None, get_prob=False, dtype="int32", key=None):
    k = key if key is not None else next_key()
    n = 1 if shape is None else (shape if isinstance(shape, int) else shape[0])
    logp_full = jnp.log(jnp.maximum(data, 1e-30))
    logp_full = logp_full - jax.scipy.special.logsumexp(
        logp_full, axis=-1, keepdims=True)
    if data.ndim == 1:
        out = jax.random.categorical(k, logp_full, shape=(n,))
        out = out if n > 1 else out[0]
    else:
        out = jax.random.categorical(k, logp_full, axis=-1,
                                     shape=(n,) + data.shape[:-1]).T
        if n == 1:
            out = out[..., 0]
    samples = out.astype(jnp.dtype(dtype))
    if get_prob:
        logp = jnp.take_along_axis(
            jnp.broadcast_to(logp_full, out.shape + logp_full.shape[-1:]),
            out[..., None].astype(jnp.int32), axis=-1)[..., 0]
        return samples, logp
    return samples
