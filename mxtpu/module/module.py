"""Module (parity: python/mxnet/module/module.py).

The reference's Module split batches across per-GPU executors
(DataParallelExecutorGroup). On TPU a single Executor runs the graph and
SPMD sharding is XLA's job, so the executor-group machinery collapses to
one executor; a ctx LIST dp-shards the batch across those devices via
GSPMD (params replicated, grads globally reduced) — see _data_sharding.
"""

from __future__ import annotations

import logging

import jax

import numpy as onp

from .. import initializer as init_mod
from .. import ndarray as nd
from .. import optimizer as opt_mod
from ..base import MXTPUError
from ..context import cpu
from ..ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._state_names = list(state_names or [])
        # a LIST of contexts is the reference's DataParallelExecutorGroup
        # request (module/executor_group.py: slice the batch across ctxs);
        # here GSPMD absorbs it — see _data_sharding below
        self._context_group = list(context) if isinstance(
            context, (list, tuple)) else None
        self._context = (self._context_group[0] if self._context_group
                         else context) if context is not None else cpu()
        self._data_mesh = None

        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + \
            self._state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._arg_params = None
        self._aux_params = None
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._data_shapes = None
        self._label_shapes = None
        self._inputs_need_grad = False

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """(parity: Module.load over save_checkpoint files)"""
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg_params, aux_params)
        if save_optimizer_states and self._updater is not None:
            with open("%s-%04d.states" % (prefix, epoch), "wb") as f:
                f.write(self._updater.get_states())

    # -- binding ----------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, tuple(o.shape)) for n, o in
                zip(self.output_names, self._exec.outputs)]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        self._data_shapes = list(data_shapes)
        self._label_shapes = list(label_shapes) if label_shapes else []

        shapes = {}
        for desc in self._data_shapes + self._label_shapes:
            name, shape = desc[0], desc[1]
            shapes[name] = tuple(shape)
        arg_shapes, _, aux_shapes = self._symbol.infer_shape_partial(**shapes)
        arg_names = self._symbol.list_arguments()
        args = {}
        grad_req_dict = {}
        for name, shp in zip(arg_names, arg_shapes or [None] * len(arg_names)):
            shp = shapes.get(name, shp)
            if shp is None:
                raise MXTPUError(
                    f"bind: cannot infer shape of {name}; provide "
                    "data/label shapes covering it")
            args[name] = nd.zeros(shp)
            if name in self._param_names and name not in \
                    self._fixed_param_names and for_training:
                grad_req_dict[name] = grad_req if isinstance(grad_req, str) \
                    else grad_req.get(name, "write")
            elif name in self._data_names and inputs_need_grad:
                grad_req_dict[name] = "write"
            else:
                grad_req_dict[name] = "null"
        auxes = {}
        aux_names = self._aux_names
        for name, shp in zip(aux_names, aux_shapes or [None] * len(aux_names)):
            shp = shapes.get(name, shp)
            auxes[name] = nd.zeros(shp) if shp else nd.zeros(())
        from ..executor import Executor
        self._exec = Executor(self._symbol, self._context, args, None,
                              grad_req_dict, auxes)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())
        elif self.params_initialized:
            # Module.load path: push loaded params into the executor
            self._exec.copy_params_from(self._arg_params, self._aux_params,
                                        allow_extra_params=True)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        self._params_replicated = False  # fresh host arrays: re-replicate
        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        elif isinstance(initializer, str):
            initializer = init_mod.create(initializer)

        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            src = (arg_params or {}).get(name)
            if src is not None:
                arr._rebind(src.data.astype(arr.data.dtype))
            else:
                if arg_params is not None and not allow_missing and not \
                        self.params_initialized:
                    raise MXTPUError(f"arg_params missing {name}")
                initializer(init_mod.InitDesc(name), arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            src = (aux_params or {}).get(name)
            if src is not None:
                arr._rebind(src.data.astype(arr.data.dtype))
            else:
                initializer(init_mod.InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    # -- optimizer --------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        optimizer_params = dict(optimizer_params or {})
        if isinstance(optimizer, str):
            batch_size = self._data_shapes[0][1][0]
            optimizer_params.setdefault("rescale_grad", 1.0 / batch_size)
            optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        from .. import kvstore as kv_mod
        if kvstore:
            kv = kv_mod.create(kvstore) if isinstance(kvstore, str) else \
                kvstore
            self._kvstore = kv
        self.optimizer_initialized = True
        if hasattr(self, "_preload_opt_states"):
            with open(self._preload_opt_states, "rb") as f:
                self._updater.set_states(f.read())
            del self._preload_opt_states

    # -- execution --------------------------------------------------------
    def _data_sharding(self):
        """Multi-device data parallelism through the Module API (parity:
        DataParallelExecutorGroup, module/executor_group.py — the
        reference slices the batch across contexts and runs one executor
        per GPU; here ONE executor runs with the batch dp-sharded across
        the context group's devices and GSPMD/XLA inserts the collectives,
        so params stay replicated and grads come out globally reduced).

        Returns None when the host has fewer real devices than requested
        contexts (the reference tolerated over-committed ctx lists by
        round-robining executors; the GSPMD equivalent is to run
        single-device)."""
        if self._data_mesh is None:
            from jax.sharding import Mesh

            devs = [c.to_jax_device() for c in self._context_group]
            if any(d is None for d in devs):
                devs = jax.devices()[:len(self._context_group)]
            unique = list(dict.fromkeys(devs))
            if len(unique) < len(self._context_group):
                self.logger.warning(
                    "Module: %d contexts but only %d distinct devices — "
                    "running single-device (over-committed ctx list)",
                    len(self._context_group), len(unique))
                self._data_mesh = False
            else:
                self._data_mesh = Mesh(onp.asarray(devs), ("dp",))
        if self._data_mesh is False:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        return (NamedSharding(self._data_mesh, P("dp")),
                NamedSharding(self._data_mesh, P()))

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = arr
        if self._context_group and len(self._context_group) > 1:
            sh = self._data_sharding()
            ndev = len(self._context_group)
            batch_ok = sh is not None and all(
                (a.shape[0] % ndev) == 0 for a in feed.values()
                if getattr(a, "ndim", 0))
            if batch_ok:
                batch_sh, repl_sh = sh
                for name, arr in feed.items():
                    arr = arr if isinstance(arr, NDArray) else \
                        nd.array(arr)
                    feed[name] = NDArray(
                        jax.device_put(arr.data, batch_sh))
                if not getattr(self, "_params_replicated", False):
                    # once per bind/param change, not per batch
                    for d in (self._exec.arg_dict, self._exec.aux_dict):
                        for name, val in d.items():
                            if name not in feed:
                                val._rebind(
                                    jax.device_put(val.data, repl_sh))
                    self._params_replicated = True
            # else: uneven tail batch (or over-committed ctx list) runs
            # unsharded — the reference's executor group sliced/padded
            # such batches; single-device is the GSPMD analogue
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        for i, name in enumerate(self._param_names):
            w = self._exec.arg_dict[name]
            g = self._exec.grad_dict.get(name)
            if g is None:
                continue
            self._updater(i, g, w)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self._inputs_need_grad
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update_dict(
            dict(zip(self._label_names, labels)),
            dict(zip(self.output_names, self._exec.outputs)))

    def install_monitor(self, mon):
        assert self.binded
        mon.install()
