"""BucketingModule (parity: python/mxnet/module/bucketing_module.py).

The reference kept one executor per sequence-length bucket sharing weights
— its answer to dynamic shapes. On TPU the same idea is a per-bucket jit
cache: each bucket key binds a Module whose executors share the parameter
arrays of the largest (default) bucket, so XLA compiles one program per
bucket shape (SURVEY §3.4 "jit cache keyed on padded bucket shapes").
"""

from __future__ import annotations

import logging

from ..base import MXTPUError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        sym, _, _ = self._call_sym_gen(self._default_bucket_key)
        return sym.list_outputs()

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._call_sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names,
                      state_names=self._state_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """(parity: BucketingModule.switch_bucket — per-bucket executors
        sharing the default bucket's parameter arrays)"""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self.for_training,
                        force_rebind=False,
                        shared_module=self._buckets[
                            self._default_bucket_key])
            if not module.params_initialized and \
                    self._buckets[self._default_bucket_key].params_initialized:
                module.set_params(
                    *self._buckets[self._default_bucket_key].get_params())
            if self.optimizer_initialized:
                default = self._buckets[self._default_bucket_key]
                module._optimizer = default._optimizer
                module._updater = default._updater
                module.optimizer_initialized = True
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        assert self.binded
        self._curr_module.init_params(initializer, arg_params, aux_params,
                                      allow_missing, force_init, allow_extra)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        assert self.binded
        self._curr_module.set_params(arg_params, aux_params, allow_missing,
                                     force_init, allow_extra)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params, force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        key = getattr(data_batch, "bucket_key", None) or \
            self._default_bucket_key
        self.switch_bucket(key, data_batch.provide_data
                           or [(n, tuple(a.shape)) for n, a in
                               zip(self.data_names, data_batch.data)],
                           data_batch.provide_label)
        # sync shared params into the bucket's executor
        if self._curr_bucket_key != self._default_bucket_key:
            self._curr_module.set_params(
                *self._buckets[self._default_bucket_key].get_params())
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()
        if self._curr_bucket_key != self._default_bucket_key:
            self._buckets[self._default_bucket_key].set_params(
                *self._curr_module.get_params())

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)
