"""BaseModule: the classic symbolic training loop (parity:
python/mxnet/module/base_module.py — fit/score/predict/forward_backward)."""

from __future__ import annotations

import logging
import time
from collections import namedtuple

import numpy as onp

from .. import metric as metric_mod
from .. import ndarray as nd
from ..base import MXTPUError

__all__ = ["BaseModule", "BatchEndParam"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _as_metric(m):
    if isinstance(m, metric_mod.EvalMetric):
        return m
    return metric_mod.create(m)


class BaseModule:
    """Abstract module; concrete subclasses implement bind/init_params/
    forward/backward/update/get_outputs/update_metric."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract interface ----------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    # -- conveniences over the abstract set -------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """(parity: BaseModule.score)"""
        assert self.binded and self.params_initialized
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                _call_list(batch_end_callback, BatchEndParam(
                    epoch=epoch, nbatch=nbatch, eval_metric=eval_metric,
                    locals=locals()))
        if score_end_callback is not None:
            _call_list(score_end_callback, BatchEndParam(
                epoch=epoch, nbatch=nbatch, eval_metric=eval_metric,
                locals=locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """(parity: BaseModule.predict)"""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [out[0:out.shape[0] - pad].copy()
                    for out in self.get_outputs()]
            output_list.append(outs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    "Cannot merge batches: different number of outputs"
            output_list2 = [nd.concat(*[out[i] for out in output_list],
                                      dim=0)
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The canonical train loop (parity: BaseModule.fit — SURVEY §3.4)."""
        assert num_epoch is not None, "please specify number of epochs"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    _call_list(batch_end_callback, BatchEndParam(
                        epoch=epoch, nbatch=nbatch,
                        eval_metric=eval_metric, locals=locals()))
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            arg_p, aux_p = self.get_params()
            if epoch_end_callback is not None:
                _call_list(epoch_end_callback, epoch, self.symbol, arg_p,
                           aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    @property
    def symbol(self):
        return self._symbol

    def install_monitor(self, mon):
        mon.install()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        save_dict = nd.load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise MXTPUError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)


def _call_list(cb, *args):
    if isinstance(cb, (list, tuple)):
        for c in cb:
            c(*args)
    else:
        cb(*args)
