"""Basic NN layers (parity: python/mxnet/gluon/nn/basic_layers.py).

Sequential/HybridSequential containers, Dense, Dropout, BatchNorm, Embedding,
Flatten, InstanceNorm, LayerNorm, GroupNorm, Lambda/HybridLambda.

Deferred shape inference: each layer overrides ``infer_shape`` to derive its
parameter shapes from the input (see block.py module docstring for the
divergence note vs the reference's symbolic graph inference).
"""

from __future__ import annotations

import numpy as onp

from ... import autograd
from ...base import MXTPUError
from ...ndarray import NDArray
from ..block import Block, HybridBlock
from .activations import Activation

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "GroupNorm", "Flatten",
           "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stack Blocks sequentially (parity: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {block!r}" for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(
                isinstance(c, HybridBlock) for c in self._children.values()):
            import warnings
            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance.",
                stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack HybridBlocks sequentially (parity: nn.HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {block!r}" for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer y = act(x·W^T + b) (parity: nn.Dense; weight
    layout (units, in_units) matching the reference's FullyConnected)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x, *args):
        in_units = (int(onp.prod(x.shape[1:])) if self._flatten
                    else x.shape[-1])
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        act = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        shape = self.weight.shape
        return (f"{type(self).__name__}({shape[1] if shape[1] else None} -> "
                f"{shape[0]}, "
                f"{'linear' if self.act is None else self.act._act_type})")


class Dropout(HybridBlock):
    """Dropout (parity: nn.Dropout; axes= for broadcast dropout)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F.identity(x)

    def __repr__(self):
        return f"{type(self).__name__}(p = {self._rate}, axes={self._axes})"


class Embedding(HybridBlock):
    """Index → vector lookup (parity: nn.Embedding).

    sparse_grad=True gives the weight a row_sparse gradient: accumulation
    stays dense on device (XLA scatter-add), but the parameter records the
    touched row ids of every RECORDED eager forward (unioned until the
    optimizer consumes the grad), so Parameter.grad() compacts to
    (indices, values) and SGD updates only those rows — the reference's
    large-embedding workflow (src/operator/tensor/indexing_op.cc
    EmbeddingOpBackward row_sparse path) with TPU-native accumulation.
    Constraints (as in the reference): the weight must not be shared with
    dense-grad consumers, and hybridized forwards fall back to dense grads
    (no ids are recordable under tracing — grad() then returns the dense
    buffer, which is always exact)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        if self._sparse_grad and autograd.is_recording():
            import jax
            import jax.numpy as jnp
            xd = x.data if hasattr(x, "data") else x
            if not isinstance(xd, jax.core.Tracer):  # eager only
                self.weight._accumulate_sparse_row_ids(
                    jnp.unique(xd.astype(jnp.int32).ravel()))
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_dim} -> "
                f"{self._output_dim}, {self.weight.dtype})")


class BatchNorm(HybridBlock):
    """Batch normalization (parity: nn.BatchNorm over src/operator/nn/
    batch_norm.cc).  Running stats are aux parameters (grad_req='null')
    updated by this layer after the pure op — under hybridize they thread
    through the compiled program as explicit aux outputs (see cached_op.py).
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self._momentum = momentum
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (channels,)

    def cast(self, dtype):
        if str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # norm stats stay fp32 (parity: BN fp16 rule)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        training = autograd.is_training()
        if training and not self._kwargs["use_global_stats"]:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var,
                output_mean_var=True, **self._kwargs)
            with autograd.pause():
                m = self._momentum
                rm = self.running_mean.data(None)
                rv = self.running_var.data(None)
                rm._rebind(rm.data * m + mean.data * (1 - m))
                rv._rebind(rv.data * m + var.data * (1 - m))
            return out
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return (f"{type(self).__name__}(axis={self._axis}, "
                f"momentum={self._momentum}, in_channels={in_channels})")


class InstanceNorm(HybridBlock):
    """Instance normalization (parity: nn.InstanceNorm)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self._epsilon = epsilon
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta,
                              eps=self._epsilon).swapaxes(1, self._axis)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return (f"{type(self).__name__}(eps={self._epsilon}, "
                f"axis={self._axis}, in_channels={in_channels})")


class LayerNorm(HybridBlock):
    """Layer normalization (parity: nn.LayerNorm / layer_norm.cc)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon, "axis": axis}
        self._axis = axis
        self._epsilon = epsilon
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return (f"{type(self).__name__}(eps={self._epsilon}, "
                f"axis={self._axis}, in_channels={in_channels})")


class GroupNorm(HybridBlock):
    """Group normalization (parity: nn.GroupNorm, 1.6+)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[1]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)

    def __repr__(self):
        return (f"{type(self).__name__}(groups={self._num_groups}, "
                f"eps={self._epsilon})")


class Flatten(HybridBlock):
    """Collapse all but batch dim (parity: nn.Flatten)."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return type(self).__name__


class Lambda(Block):
    """Wrap a function as a Block (parity: nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            assert hasattr(nd, function), \
                f"Function name {function} is not found in ndarray."
            self._func_impl = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError(
                "Unrecognized function in lambda: {} of type {}".format(
                    function, type(function)))

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"{type(self).__name__}({self._func_name})"


class HybridLambda(HybridBlock):
    """Wrap a function as a HybridBlock (parity: nn.HybridLambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            assert hasattr(nd, function), \
                f"Function name {function} is not found in ndarray."
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError(
                "Unrecognized function in lambda: {} of type {}".format(
                    function, type(function)))

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"{type(self).__name__}({self._func_name})"
