"""Activation layers (parity: python/mxnet/gluon/nn/activations.py)."""

from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish",
           "GELU"]


class Activation(HybridBlock):
    """Apply a named activation (parity: nn.Activation).

    Supported: relu, sigmoid, tanh, softrelu, softsign.
    """

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"{type(self).__name__}({self._act_type})"


class LeakyReLU(HybridBlock):
    """max(x, alpha*x) (parity: nn.LeakyReLU)."""

    def __init__(self, alpha, **kwargs):
        assert alpha >= 0, "Slope coefficient for LeakyReLU must be no less than 0."
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"{type(self).__name__}({self._alpha})"


class PReLU(HybridBlock):
    """Learnable-slope leaky relu (parity: nn.PReLU)."""

    def __init__(self, alpha_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(1,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    """Exponential linear unit (parity: nn.ELU)."""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """Scaled ELU (parity: nn.SELU)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    """x * sigmoid(beta*x) (parity: nn.Swish)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    """Gaussian error linear unit (parity: nn.GELU)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")
