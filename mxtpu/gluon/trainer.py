"""Trainer (parity: python/mxnet/gluon/trainer.py).

Binds a set of Parameters to an Optimizer and (optionally) a KVStore:
``step(batch_size)`` = allreduce grads → apply updates, exactly the
reference's flow (SURVEY §3.3).  On TPU the kvstore reduce is an in-process
sum for ``local``/``device`` and an XLA psum across processes for
``dist_tpu_sync``; ``update_on_kvstore`` keeps its observable semantics
(optimizer runs inside the store) even though there are no server processes.
"""

from __future__ import annotations

import warnings

from .. import optimizer as opt
from ..base import MXTPUError
from ..kvstore import KVStore, create as kv_create
from ..ndarray.ndarray import NDArray, invoke_op
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    """``guard=True`` (default: the ``MXTPU_GUARDIAN`` env var) adds
    in-step divergence containment (docs/guardian.md): ONE fused
    ``multi_all_finite`` reduction checks every gradient on device
    (single host sync) before the allreduce; a non-finite verdict skips
    the allreduce and the optimizer update entirely, so params and
    optimizer state are bit-identical to not having stepped (and NaNs
    never reach a kvstore that updates on push).  The verdict is
    exposed as ``trainer.last_step_ok``.  On a distributed kvstore the
    per-worker verdicts are AND-reduced through one extra scalar
    collective so every worker takes the same skip/apply branch (a
    unilateral skip would desync the synchronized allreduce).  With an
    AMP fp16 loss scaler attached (``amp.init_trainer``), the same
    check drives the scaler's grow/backoff automaton inside ``step`` —
    no separate per-param overflow loop.

    Scope: the pre-reduce check sees per-device/per-worker addends, and
    a reduction can overflow a narrow dtype even when every addend is
    finite.  On the ``update_on_kvstore=False`` path the reduced grads
    land back in local buffers and a second post-reduce check closes
    that gap; with ``update_on_kvstore=True`` the optimizer applies
    INSIDE the push, so reduce-time overflow there is outside the
    containment guarantee (keep fp32 grads, or update_on_kvstore=False,
    for AMP runs near the fp16 ceiling)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None, guard=None):
        param_list = []
        if isinstance(params, (dict, ParameterDict)):
            for key in sorted(list(params.keys())):
                param_list.append(params[key])
            params = param_list
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._distributed = None
        self._params_to_init = []
        if guard is None:
            from ..resilience.guardian import guard_enabled_default
            guard = guard_enabled_default()
        self._guard = bool(guard)
        self.last_step_ok = True
        self._narrow_grads = None  # lazy: any fp16/bf16 grad buffers?
        self._reset_kvstore()

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx() if param._data or param._deferred_init \
                else [None]
            assert contexts is None or contexts == ctx, (
                "All Parameters must be initialized on the same set of "
                f"contexts, but Parameter {param.name} is initialized on "
                f"{ctx} while previous Parameters are initialized on "
                f"{contexts}.")
            contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _reset_kvstore(self):
        if self._kvstore and isinstance(self._kvstore, KVStore) and \
                "dist" in self._kvstore.type:
            raise RuntimeError(
                "Cannot reset distributed KVStore.")
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = [param for param in self._params]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        if isinstance(kvstore, str):
            # parity with _create_kvstore: no kvstore for a single device
            # unless a dist type is requested
            if "dist" in kvstore:
                kvstore = kv_create(kvstore)
            elif len(self._contexts) > 1:
                kvstore = kv_create(kvstore)
            else:
                kvstore = None
        if kvstore is not None:
            self._distributed = "dist" in kvstore.type
            if update_on_kvstore is None:
                update_on_kvstore = True
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore = kvstore
            self._update_on_kvstore = update_on_kvstore
        else:
            self._distributed = False
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    def _init_params(self):
        assert self._kv_initialized, \
            "Cannot initialize parameters in KVStore when KVStore is not " \
            "initialized."
        params_to_init = []
        if self._kvstore:
            for param in self._params_to_init:
                if param._deferred_init:
                    params_to_init.append(param)
                else:
                    idx = self._param2idx[param.name]
                    self._kvstore.init(idx, param.data(self._contexts[0]))
        self._params_to_init = params_to_init

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning(
                "Optimizer has to be defined before its learning rate can "
                "be accessed.")
        return self._optimizer.learning_rate if hasattr(
            self._optimizer, "learning_rate") else self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning(
                "Optimizer has to be defined before its learning rate is "
                "mutated.")
        self._optimizer.set_learning_rate(lr)

    def _row_sparse_pull(self, parameter, out, row_id, full_idx=False):
        # sparse descoped v1: dense pull
        if self._kvstore:
            self._kvstore.pull(self._param2idx[parameter.name], out=out)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads, then apply optimizer updates scaled by
        1/batch_size (parity: Trainer.step).  Guarded/AMP trainers run
        the fused finiteness check first and skip the update on a
        non-finite verdict — containment, not propagation."""
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        # the containment gate runs BEFORE the allreduce: with
        # update_on_kvstore the optimizer applies inside the push, so a
        # post-reduce check could not stop a NaN from poisoning the
        # store's weights (distributed workers AND their local verdicts
        # into one global verdict first — see _maybe_skip_update)
        post = self._post_reduce_applicable()
        # when a post-reduce re-check will run, IT owns the step's final
        # verdict — the scaler must be driven exactly once per step, so
        # the pre-reduce check defers the clean-step drive to it (a
        # window-boundary grow here would otherwise cancel the
        # post-reduce backoff, leaving the scale un-backed-off on an
        # overflowing step)
        if self._maybe_skip_update(drive_scaler_on_ok=not post):
            return
        self._allreduce_grads()
        if post and self._post_reduce_overflow():
            return
        self._update(ignore_stale_grad)

    # -- in-step containment (docs/guardian.md) --------------------------
    def _grads_all_finite(self):
        """ONE fused on-device multi_all_finite reduction over every
        gradient on every device, one host sync — the guarded step's
        verdict."""
        grads = []
        for param in self._params:
            if param.grad_req != "null":
                # dense buffers, not list_grad(): a row_sparse view can't
                # feed multi_all_finite, and the dense buffer's verdict is
                # identical (untouched rows accumulated finite zeros)
                grads.extend(param._list_dense_grad())
        if not grads:
            return True
        ok = invoke_op("multi_all_finite", tuple(grads),
                       {"num_arrays": len(grads)})
        return bool(ok.asnumpy())

    def _maybe_skip_update(self, drive_scaler_on_ok=True):
        """Containment gate between allreduce and update: with guarding
        (or an AMP loss scaler) active, a non-finite gradient anywhere
        skips the whole update — params and optimizer state stay
        bit-identical to not stepping.  Returns True when the update
        must be skipped.  An overflow verdict always drives the scaler's
        backoff (it is final — the step is skipped); the clean-step
        drive is deferred to the post-reduce check when one will run
        (``drive_scaler_on_ok=False``), so the scaler sees exactly one
        verdict per step."""
        scaler = getattr(self, "_amp_loss_scaler", None)
        if not self._guard and scaler is None:
            return False
        ok = self._grads_all_finite()
        if self._distributed:
            # the verdict must be GLOBAL: workers see different local
            # grads, and a unilateral skip would desync the synchronized
            # allreduce/push below (everyone else blocks in the
            # collective).  AND the per-worker verdicts — every worker
            # runs this tiny reduce every guarded step, so the branch
            # taken is identical on all of them (and the AMP scalers
            # stay in lockstep too).
            import jax
            import numpy as onp

            from ..parallel import collectives as _coll
            total = _coll.all_reduce_across_processes(
                onp.float32(1.0 if ok else 0.0))
            ok = bool(float(total) >= jax.process_count() - 0.5)
        self.last_step_ok = ok
        if scaler is not None and (drive_scaler_on_ok or not ok):
            scaler.update_scale(overflow=not ok)
        if ok:
            return False
        from ..resilience.counters import bump
        bump("guardian_skips")
        for param in self._params:
            if param.grad_req != "null":
                param._consume_sparse_row_ids()  # grads consumed anyway
        return True

    def _post_reduce_applicable(self):
        """True when a second, post-reduce finiteness check must run:
        pushpull path (update_on_kvstore applies inside the push — no
        hook point) AND a gradient dtype narrow enough for a reduce-sum
        of finite addends to overflow (fp16/bf16, or any run with an AMP
        scaler attached).  Plain fp32 training skips the second
        reduction and host sync entirely."""
        scaler = getattr(self, "_amp_loss_scaler", None)
        if ((not self._guard and scaler is None) or not self._kvstore
                or self._update_on_kvstore):
            return False
        if scaler is not None:
            return True
        if self._narrow_grads is None:
            # grad dtypes are fixed once params are initialized (a
            # cast() mid-training is not a supported flow), so scan the
            # buffers once instead of per hot-path step
            self._narrow_grads = any(
                str(g.dtype) in ("float16", "bfloat16")
                for param in self._params if param.grad_req != "null"
                for g in param._list_dense_grad())
        return self._narrow_grads

    def _post_reduce_overflow(self):
        """Second half of the containment gate (see
        :meth:`_post_reduce_applicable`): the pre-reduce check sees
        per-device addends, but their SUM can overflow a narrow grad
        dtype (fp16 near the 65504 ceiling under a large loss scale)
        even when every addend is finite.  The reduced grads sit back in
        the dense buffers, so re-checking after the reduce catches that
        and skips the update.  Owns the step's final verdict: drives the
        scaler exactly once (the pre-reduce check deferred its
        clean-step drive here)."""
        scaler = getattr(self, "_amp_loss_scaler", None)
        ok = self._grads_all_finite()
        # the verdict is already global — every worker holds the SAME
        # reduced buffers, so no cross-process AND is needed here
        if scaler is not None:
            scaler.update_scale(overflow=not ok)
        if ok:
            return False
        self.last_step_ok = False
        from ..resilience.counters import bump
        bump("guardian_skips")
        for param in self._params:
            if param.grad_req != "null":
                param._consume_sparse_row_ids()
        return True

    def _check_and_rescale_grad(self, scale):
        if self._update_on_kvstore and self._distributed and \
                self._kv_initialized:
            if self._optimizer.rescale_grad != scale:
                raise UserWarning(
                    "Possible change in the `batch_size` from previous "
                    "`step` detected. Optimizer gradient normalizing "
                    "factor will not change.")
        self._optimizer.rescale_grad = scale

    def allreduce_grads(self):
        """Reduce gradients across devices/workers without updating
        (parity: allreduce_grads; for use with update())."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "allreduce_grads() when parameters are updated on kvstore " \
            "is not supported. Try setting `update_on_kvstore` to False " \
            "when creating trainer."
        self._allreduce_grads()

    def _allreduce_grads(self):
        if not self._kvstore:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                idx = self._param2idx[param.name]
                # dense buffers: the reduce writes back in place; sparse
                # views are re-derived from the reduced buffer at update
                dense = param._list_dense_grad()
                if not self._update_on_kvstore:
                    self._kvstore.pushpull(idx, dense, out=dense,
                                           priority=-i)
                else:
                    self._kvstore.push(idx, param.list_grad(), priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        """Apply updates only (parity: update; requires allreduce_grads
        first in kvstore mode)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._check_and_rescale_grad(self._scale / batch_size)
        if self._maybe_skip_update():
            return
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        local = []  # (index, param) updated in-process (not on kvstore)
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._kvstore and self._update_on_kvstore:
                # weights live in the store; pull them back
                idx = self._param2idx[param.name]
                self._kvstore.pull(idx, out=param.list_data(), priority=-i)
                param._consume_sparse_row_ids()
                continue
            local.append((i, param))
        if local and not self._fused_sgd_update(local):
            for i, param in local:
                for upd, arr, grad in zip(self._updaters, param.list_data(),
                                          param.list_grad()):
                    upd(i, grad, arr)
                param._consume_sparse_row_ids()  # grad consumed: new epoch
        else:
            for _, param in local:
                param._consume_sparse_row_ids()

    # -- fused multi-tensor update path ----------------------------------
    def _fusable_sgd(self, local):
        """Whether the optimizer-update loop can route through the fused
        multi_sgd_update / multi_mp_sgd_update registry ops: plain SGD
        (subclasses may override the rule), one device, dense weights and
        grads.  Anything else falls back to the per-param updaters."""
        if type(self._optimizer) is not opt.SGD:
            return False
        if len(self._updaters) != 1 or len(self._contexts) > 1:
            return False
        for _, param in local:
            if param._grad_stype != "default":
                return False
            w, g = param.list_data()[0], param.list_grad()[0]
            if w.stype != "default" or g.stype != "default":
                return False
        return True

    def _fused_sgd_update(self, local):
        """One engine dispatch per same-dtype parameter group instead of
        one per parameter (parity: the reference's aggregate SGD update
        via multi_sgd_update — MXNET_OPTIMIZER_AGGREGATION_SIZE), routed
        through the registered preloaded_multi_(mp_)sgd(_mom)_update
        fused ops.  The preloaded variants take lr/wd as trailing 1-D
        tensors, which keeps the update bit-identical to the per-param
        jitted rule (a python-float lr would constant-fold differently
        under XLA) AND keeps the compiled signature stable across lr
        schedule changes.  Under ``engine.bulk`` the whole update loop is
        ONE bulked segment.  Returns False when not applicable."""
        if not self._fusable_sgd(local):
            return False
        import jax.numpy as jnp

        optimizer = self._optimizer
        upd = self._updaters[0]

        groups = {}  # weight dtype -> list of (index, weight, grad)
        for i, param in local:
            w = param.list_data()[0]
            if i not in upd.states:
                upd.states[i] = optimizer.create_state_multi_precision(
                    i, w)
                upd.states_synced[i] = True
            groups.setdefault(str(w.dtype), []).append(
                (i, w, param.list_grad()[0]))

        momentum = optimizer.momentum
        clip = (optimizer.clip_gradient
                if optimizer.clip_gradient is not None else -1.0)
        pending_states = []
        for dtype, group in groups.items():
            mp = optimizer.multi_precision and dtype == "bfloat16"
            lrs, wds, data = [], [], []
            for i, w, g in group:
                optimizer._update_count(i)
                lrs.append(optimizer._get_lr(i))
                wds.append(optimizer._get_wd(i))
                state = upd.states[i]
                data.extend((w, g))
                if mp:
                    w32, mom = state
                    if momentum != 0.0:
                        data.append(NDArray(mom))
                    data.append(NDArray(w32))
                elif momentum != 0.0:
                    data.append(NDArray(state))
            data.append(NDArray(jnp.asarray(lrs, jnp.float32)))
            data.append(NDArray(jnp.asarray(wds, jnp.float32)))
            op_name = "preloaded_multi_%ssgd%s" % (
                "mp_" if mp else "",
                "_mom_update" if momentum != 0.0 else "_update")
            kwargs = {"rescale_grad": optimizer.rescale_grad,
                      "clip_gradient": clip, "num_weights": len(group)}
            if momentum != 0.0:
                kwargs["momentum"] = momentum
            outs = invoke_op(op_name, tuple(data), kwargs)
            if isinstance(outs, NDArray):
                outs = (outs,)
            stride = len(outs) // len(group)
            for k, (i, w, _g) in enumerate(group):
                res = outs[k * stride:(k + 1) * stride]
                w._rebind_from(res[0])
                pending_states.append((i, mp, res))
        # state readback AFTER every group dispatched: reading ._data
        # forces a bulk flush, so doing it per-group would split the
        # bulked update into one segment per dtype group.  Here the
        # first read flushes ONE segment holding the whole loop.
        # (momentum=0 non-mp groups have no state and stay fully lazy.)
        for i, mp, res in pending_states:
            if mp and momentum != 0.0:
                upd.states[i] = (res[2]._data, res[1]._data)
            elif mp:
                upd.states[i] = (res[1]._data, None)
            elif momentum != 0.0:
                upd.states[i] = res[1]._data
        return True

    def save_states(self, fname):
        """Save optimizer/updater states (parity: save_states)."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            assert not self._params_to_init, \
                "Cannot save trainer states when some parameters are not " \
                "yet initialized in kvstore."
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            # atomic write + CRC32 manifest sidecar (docs/guardian.md):
            # a crash mid-save leaves the previous states file intact
            from ..resilience import checkpoint as _ckpt
            _ckpt.write_verified(
                fname, self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Load optimizer/updater states (parity: load_states).  A CRC
        manifest, when present, is verified first — damaged files raise
        a typed :class:`~mxtpu.resilience.CorruptCheckpointError`
        instead of misparsing."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            # the kvstore's load verifies — one read, one verify
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            from ..resilience import checkpoint as _ckpt
            with open(fname, "rb") as f:
                states = f.read()
            _ckpt.verify(fname, data=states)
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
        param_dict = {i: param for i, param in enumerate(self._params)}
        self._optimizer.param_dict = param_dict
