"""Trainer (parity: python/mxnet/gluon/trainer.py).

Binds a set of Parameters to an Optimizer and (optionally) a KVStore:
``step(batch_size)`` = allreduce grads → apply updates, exactly the
reference's flow (SURVEY §3.3).  On TPU the kvstore reduce is an in-process
sum for ``local``/``device`` and an XLA psum across processes for
``dist_tpu_sync``; ``update_on_kvstore`` keeps its observable semantics
(optimizer runs inside the store) even though there are no server processes.
"""

from __future__ import annotations

import warnings

from .. import optimizer as opt
from ..base import MXTPUError
from ..kvstore import KVStore, create as kv_create
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        param_list = []
        if isinstance(params, (dict, ParameterDict)):
            for key in sorted(list(params.keys())):
                param_list.append(params[key])
            params = param_list
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._distributed = None
        self._params_to_init = []
        self._reset_kvstore()

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx() if param._data or param._deferred_init \
                else [None]
            assert contexts is None or contexts == ctx, (
                "All Parameters must be initialized on the same set of "
                f"contexts, but Parameter {param.name} is initialized on "
                f"{ctx} while previous Parameters are initialized on "
                f"{contexts}.")
            contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _reset_kvstore(self):
        if self._kvstore and isinstance(self._kvstore, KVStore) and \
                "dist" in self._kvstore.type:
            raise RuntimeError(
                "Cannot reset distributed KVStore.")
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = [param for param in self._params]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        if isinstance(kvstore, str):
            # parity with _create_kvstore: no kvstore for a single device
            # unless a dist type is requested
            if "dist" in kvstore:
                kvstore = kv_create(kvstore)
            elif len(self._contexts) > 1:
                kvstore = kv_create(kvstore)
            else:
                kvstore = None
        if kvstore is not None:
            self._distributed = "dist" in kvstore.type
            if update_on_kvstore is None:
                update_on_kvstore = True
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore = kvstore
            self._update_on_kvstore = update_on_kvstore
        else:
            self._distributed = False
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    def _init_params(self):
        assert self._kv_initialized, \
            "Cannot initialize parameters in KVStore when KVStore is not " \
            "initialized."
        params_to_init = []
        if self._kvstore:
            for param in self._params_to_init:
                if param._deferred_init:
                    params_to_init.append(param)
                else:
                    idx = self._param2idx[param.name]
                    self._kvstore.init(idx, param.data(self._contexts[0]))
        self._params_to_init = params_to_init

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning(
                "Optimizer has to be defined before its learning rate can "
                "be accessed.")
        return self._optimizer.learning_rate if hasattr(
            self._optimizer, "learning_rate") else self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning(
                "Optimizer has to be defined before its learning rate is "
                "mutated.")
        self._optimizer.set_learning_rate(lr)

    def _row_sparse_pull(self, parameter, out, row_id, full_idx=False):
        # sparse descoped v1: dense pull
        if self._kvstore:
            self._kvstore.pull(self._param2idx[parameter.name], out=out)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads, then apply optimizer updates scaled by
        1/batch_size (parity: Trainer.step)."""
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _check_and_rescale_grad(self, scale):
        if self._update_on_kvstore and self._distributed and \
                self._kv_initialized:
            if self._optimizer.rescale_grad != scale:
                raise UserWarning(
                    "Possible change in the `batch_size` from previous "
                    "`step` detected. Optimizer gradient normalizing "
                    "factor will not change.")
        self._optimizer.rescale_grad = scale

    def allreduce_grads(self):
        """Reduce gradients across devices/workers without updating
        (parity: allreduce_grads; for use with update())."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "allreduce_grads() when parameters are updated on kvstore " \
            "is not supported. Try setting `update_on_kvstore` to False " \
            "when creating trainer."
        self._allreduce_grads()

    def _allreduce_grads(self):
        if not self._kvstore:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                idx = self._param2idx[param.name]
                # dense buffers: the reduce writes back in place; sparse
                # views are re-derived from the reduced buffer at update
                dense = param._list_dense_grad()
                if not self._update_on_kvstore:
                    self._kvstore.pushpull(idx, dense, out=dense,
                                           priority=-i)
                else:
                    self._kvstore.push(idx, param.list_grad(), priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        """Apply updates only (parity: update; requires allreduce_grads
        first in kvstore mode)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._check_and_rescale_grad(self._scale / batch_size)
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._kvstore and self._update_on_kvstore:
                # weights live in the store; pull them back
                idx = self._param2idx[param.name]
                self._kvstore.pull(idx, out=param.list_data(), priority=-i)
                param._consume_sparse_row_ids()
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)
            param._consume_sparse_row_ids()  # grad consumed: new id epoch

    def save_states(self, fname):
        """Save optimizer/updater states (parity: save_states)."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            assert not self._params_to_init, \
                "Cannot save trainer states when some parameters are not " \
                "yet initialized in kvstore."
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Load optimizer/updater states (parity: load_states)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
        param_dict = {i: param for i, param in enumerate(self._params)}
        self._optimizer.param_dict = param_dict
