"""Gluon data API (parity: python/mxnet/gluon/data/).

Dataset / Sampler / DataLoader with host-side worker processes and a
device-prefetch double buffer — the TPU-native replacement for the
reference's C++ threaded prefetching iterators (src/io/iter_prefetcher.h).
"""

from . import dataset
from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset
from . import sampler
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler
from . import dataloader
from .dataloader import DataLoader
from . import vision
