"""Datasets (parity: python/mxnet/gluon/data/dataset.py — Dataset,
SimpleDataset, ArrayDataset, RecordFileDataset)."""

from ... import ndarray as nd
from ...ndarray import NDArray

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: random access by index + length."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([s for s in self if fn(s)])

    def shard(self, num_shards, index):
        """Even contiguous shard — used for per-host data splits in
        multi-host data parallel (each process loads its own shard)."""
        assert 0 <= index < num_shards
        n = len(self)
        base = n // num_shards
        rem = n % num_shards
        start = base * index + min(index, rem)
        stop = start + base + (1 if index < rem else 0)
        return SimpleDataset([self[i] for i in range(start, stop)])

    def take(self, count):
        if count is None or count >= len(self):
            return self
        return SimpleDataset([self[i] for i in range(count)])

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


class SimpleDataset(Dataset):
    """Wraps any list/array-like exposing __getitem__/__len__."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    """Picklable closure applying fn to the first element only."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class ArrayDataset(Dataset):
    """Zip of equal-length arrays; single array yields scalar samples."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, (
                "All arrays must have the same length; array[0] has %d "
                "while array[%d] has %d." % (self._length, i, len(data)))
            if isinstance(data, NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO (.rec) file with .idx index
    (parity: RecordFileDataset over MXIndexedRecordIO)."""

    def __init__(self, filename):
        from ... import recordio
        idx_file = filename[:filename.rindex(".")] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")
        self._filename = filename

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
