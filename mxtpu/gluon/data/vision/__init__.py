"""Vision datasets + transforms (parity: gluon/data/vision/)."""

from . import datasets
from .datasets import (MNIST, FashionMNIST, CIFAR10, CIFAR100,
                       ImageRecordDataset, ImageFolderDataset,
                       ImageListDataset)
from . import transforms
