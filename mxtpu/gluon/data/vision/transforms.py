"""Vision transforms (parity: python/mxnet/gluon/data/vision/transforms.py).

Transforms are Blocks operating on HWC images (uint8 NDArray or numpy).
Deterministic tensor transforms (ToTensor, Normalize, Cast) are
HybridBlocks — they run on-device and fuse into the jitted step; random
augmentations run host-side in DataLoader workers (numpy), which is the
right split for TPU: cheap branchy pixel work on host, dense math on chip.
"""

import numbers

import numpy as onp

from .... import ndarray as nd
from ....ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomCrop", "RandomResizedCrop",
           "RandomFlipLeftRight", "RandomFlipTopBottom", "RandomBrightness",
           "RandomContrast", "RandomSaturation", "RandomHue",
           "RandomColorJitter", "RandomLighting", "RandomGray"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)


class Compose(Sequential):
    """Sequentially compose transforms (parity: transforms.Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (parity: ToTensor)."""

    def hybrid_forward(self, F, x):
        x = x.astype("float32") / 255.0
        if x.ndim == 3:
            return F.transpose(x, (2, 0, 1))
        return F.transpose(x, (0, 3, 1, 2))


class Normalize(HybridBlock):
    """Channel-wise (x - mean) / std on CHW float input."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        mean = onp.asarray(self._mean, dtype="float32").reshape(-1, 1, 1)
        std = onp.asarray(self._std, dtype="float32").reshape(-1, 1, 1)
        return (x - nd.array(mean, ctx=x.context)) / \
            nd.array(std, ctx=x.context)


class Resize(Block):
    """Resize HWC image to `size` (w, h) or short-edge int."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image
        a = _to_np(x)
        if isinstance(self._size, numbers.Number):
            if self._keep:
                h, w = a.shape[:2]
                if w < h:
                    new_w, new_h = self._size, int(h * self._size / w)
                else:
                    new_w, new_h = int(w * self._size / h), self._size
            else:
                new_w = new_h = self._size
        else:
            new_w, new_h = self._size
        return nd.array(image.imresize_np(a, new_w, new_h,
                                          self._interpolation))


def _crop(a, x0, y0, w, h):
    return a[y0:y0 + h, x0:x0 + w]


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, numbers.Number) else size
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image
        a = _to_np(x)
        w, h = self._size
        H, W = a.shape[:2]
        if W < w or H < h:
            a = image.imresize_np(a, max(w, W), max(h, H),
                                  self._interpolation)
            H, W = a.shape[:2]
        x0, y0 = (W - w) // 2, (H - h) // 2
        return nd.array(_crop(a, x0, y0, w, h))


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, numbers.Number) else size
        self._pad = pad
        self._interpolation = interpolation

    def forward(self, x):
        a = _to_np(x)
        if self._pad:
            p = self._pad
            a = onp.pad(a, ((p, p), (p, p), (0, 0)), mode="constant")
        w, h = self._size
        H, W = a.shape[:2]
        x0 = onp.random.randint(0, max(1, W - w + 1))
        y0 = onp.random.randint(0, max(1, H - h + 1))
        return nd.array(_crop(a, x0, y0, w, h))


class RandomResizedCrop(Block):
    """Random area+aspect crop then resize (the ImageNet train transform)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, numbers.Number) else size
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image
        a = _to_np(x)
        H, W = a.shape[:2]
        area = H * W
        for _ in range(10):
            target_area = onp.random.uniform(*self._scale) * area
            log_ratio = (onp.log(self._ratio[0]), onp.log(self._ratio[1]))
            aspect = onp.exp(onp.random.uniform(*log_ratio))
            w = int(round(onp.sqrt(target_area * aspect)))
            h = int(round(onp.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = onp.random.randint(0, W - w + 1)
                y0 = onp.random.randint(0, H - h + 1)
                a = _crop(a, x0, y0, w, h)
                return nd.array(image.imresize_np(
                    a, self._size[0], self._size[1], self._interpolation))
        # fallback: center crop
        return CenterCrop(self._size, self._interpolation)(nd.array(a))


class RandomFlipLeftRight(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if onp.random.rand() < self._p:
            return nd.array(_to_np(x)[:, ::-1])
        return x if isinstance(x, NDArray) else nd.array(x)


class RandomFlipTopBottom(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if onp.random.rand() < self._p:
            return nd.array(_to_np(x)[::-1])
        return x if isinstance(x, NDArray) else nd.array(x)


class _RandomPixelJitter(Block):
    def __init__(self, factor):
        super().__init__()
        self._factor = factor

    def _alpha(self):
        return 1.0 + onp.random.uniform(-self._factor, self._factor)


class RandomBrightness(_RandomPixelJitter):
    def forward(self, x):
        a = _to_np(x).astype("float32") * self._alpha()
        return nd.array(onp.clip(a, 0, 255))


class RandomContrast(_RandomPixelJitter):
    def forward(self, x):
        a = _to_np(x).astype("float32")
        alpha = self._alpha()
        gray = (a * _GRAY_COEF).sum(axis=-1).mean()
        return nd.array(onp.clip(a * alpha + gray * (1 - alpha), 0, 255))


_GRAY_COEF = onp.array([0.299, 0.587, 0.114], dtype="float32")


class RandomSaturation(_RandomPixelJitter):
    def forward(self, x):
        a = _to_np(x).astype("float32")
        alpha = self._alpha()
        gray = (a * _GRAY_COEF).sum(axis=-1, keepdims=True)
        return nd.array(onp.clip(a * alpha + gray * (1 - alpha), 0, 255))


class RandomHue(_RandomPixelJitter):
    def forward(self, x):
        a = _to_np(x).astype("float32")
        alpha = onp.random.uniform(-self._factor, self._factor)
        u, w = onp.cos(alpha * onp.pi), onp.sin(alpha * onp.pi)
        bt = onp.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]], dtype="float32")
        t_yiq = onp.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], dtype="float32")
        t_rgb = onp.array([[1.0, 0.956, 0.621],
                           [1.0, -0.272, -0.647],
                           [1.0, -1.107, 1.705]], dtype="float32")
        m = t_rgb @ bt @ t_yiq
        return nd.array(onp.clip(a @ m.T, 0, 255))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        order = onp.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise."""

    _eigval = onp.array([55.46, 4.794, 1.148], dtype="float32")
    _eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], dtype="float32")

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = _to_np(x).astype("float32")
        alpha = onp.random.normal(0, self._alpha, size=(3,)).astype("float32")
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd.array(onp.clip(a + rgb, 0, 255))


class RandomGray(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if onp.random.rand() < self._p:
            a = _to_np(x).astype("float32")
            gray = (a * _GRAY_COEF).sum(axis=-1, keepdims=True)
            return nd.array(onp.broadcast_to(gray, a.shape).copy())
        return x if isinstance(x, NDArray) else nd.array(x)
