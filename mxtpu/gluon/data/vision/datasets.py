"""Vision datasets (parity: python/mxnet/gluon/data/vision/datasets.py).

File formats are parsed natively (MNIST idx-gzip, CIFAR pickle batches) so
on-disk datasets produced for the reference load unchanged. Downloads
require network; in air-gapped environments point `root` at pre-fetched
files.
"""

import gzip
import os
import pickle
import struct
import tarfile
import warnings

import numpy as onp

from .... import ndarray as nd
from ..dataset import Dataset, ArrayDataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset", "ImageListDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST handwritten digits; reads the standard idx-gzip files."""

    _namespace = "mnist"
    _train_data = ("train-images-idx3-ubyte.gz", None)
    _train_label = ("train-labels-idx1-ubyte.gz", None)
    _test_data = ("t10k-images-idx3-ubyte.gz", None)
    _test_label = ("t10k-labels-idx1-ubyte.gz", None)

    def __init__(self, root=os.path.join("~", ".mxtpu", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _fetch(self, fname):
        path = os.path.join(self._root, fname)
        if not os.path.exists(path):
            # try non-gz sibling
            alt = path[:-3]
            if os.path.exists(alt):
                return alt
            from ...utils import download
            url = ("https://ossci-datasets.s3.amazonaws.com/mnist/" + fname)
            download(url, path=path)
        return path

    @staticmethod
    def _read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        magic = struct.unpack(">I", data[:4])[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
        arr = onp.frombuffer(data, dtype=onp.uint8, offset=4 + 4 * ndim)
        return arr.reshape(dims)

    def _get_data(self):
        data_f, label_f = ((self._train_data[0], self._train_label[0])
                           if self._train else
                           (self._test_data[0], self._test_label[0]))
        images = self._read_idx(self._fetch(data_f))
        labels = self._read_idx(self._fetch(label_f))
        self._data = images.reshape(-1, 28, 28, 1)
        self._label = labels.astype(onp.int32)


class FashionMNIST(MNIST):
    _namespace = "fashion-mnist"

    def __init__(self, root=os.path.join("~", ".mxtpu", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root=root, train=train, transform=transform)

    def _fetch(self, fname):
        path = os.path.join(self._root, fname)
        if not os.path.exists(path):
            alt = path[:-3]
            if os.path.exists(alt):
                return alt
            from ...utils import download
            url = ("http://fashion-mnist.s3-website.eu-central-1.amazonaws"
                   ".com/" + fname)
            download(url, path=path)
        return path


class CIFAR10(_DownloadedDataset):
    """CIFAR-10; reads the python-pickle batch files."""

    _archive = "cifar-10-python.tar.gz"
    _dirname = "cifar-10-batches-py"
    _train_batches = ["data_batch_%d" % i for i in range(1, 6)]
    _test_batches = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, root=os.path.join("~", ".mxtpu", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _extract(self):
        d = os.path.join(self._root, self._dirname)
        if os.path.isdir(d):
            return d
        archive = os.path.join(self._root, self._archive)
        if not os.path.exists(archive):
            from ...utils import download
            download("https://www.cs.toronto.edu/~kriz/" + self._archive,
                     path=archive)
        with tarfile.open(archive) as tar:
            tar.extractall(self._root)
        return d

    def _get_data(self):
        d = self._extract()
        batches = self._train_batches if self._train else self._test_batches
        data, labels = [], []
        for b in batches:
            with open(os.path.join(d, b), "rb") as f:
                entry = pickle.load(f, encoding="bytes")
            data.append(entry[b"data"])
            labels.extend(entry[self._label_key])
        data = onp.concatenate(data).reshape(-1, 3, 32, 32)
        self._data = data.transpose(0, 2, 3, 1)  # HWC like the reference
        self._label = onp.asarray(labels, dtype=onp.int32)


class CIFAR100(CIFAR10):
    _archive = "cifar-100-python.tar.gz"
    _dirname = "cifar-100-python"
    _train_batches = ["train"]
    _test_batches = ["test"]

    def __init__(self, root=os.path.join("~", ".mxtpu", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._label_key = b"fine_labels" if fine_label else b"coarse_labels"
        super().__init__(root=root, train=train, transform=transform)


class ImageRecordDataset(RecordFileDataset):
    """Images packed in a RecordIO file by im2rec (parity:
    ImageRecordDataset): each record is IRHeader(label) + encoded image."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import image, recordio
        record = super().__getitem__(idx)
        header, img = recordio.unpack(record)
        img = image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """root/<class-name>/<image> layout (parity: ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".bmp"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                warnings.warn("Ignoring %s, which is not a directory." % path)
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filepath = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1].lower()
                if ext not in self._exts:
                    warnings.warn(
                        "Ignoring %s of type %s. Only support %s" % (
                            filepath, ext, ", ".join(self._exts)))
                    continue
                self.items.append((filepath, label))

    def __getitem__(self, idx):
        from .... import image
        img = image.imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageListDataset(Dataset):
    """Images given by an explicit (path, label) list file or list."""

    def __init__(self, root=".", imglist=None, flag=1):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self.items = []
        if isinstance(imglist, str):
            with open(imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    # .lst format: index \t label... \t path
                    label = [float(x) for x in parts[1:-1]]
                    self.items.append((parts[-1], onp.asarray(
                        label if len(label) > 1 else label[0])))
        else:
            for item in imglist or []:
                path, label = item[-1], item[:-1]
                if len(label) == 1:
                    label = label[0]
                self.items.append((path, onp.asarray(label)))

    def __getitem__(self, idx):
        from .... import image
        path = os.path.join(self._root, self.items[idx][0])
        return image.imread(path, self._flag), self.items[idx][1]

    def __len__(self):
        return len(self.items)
