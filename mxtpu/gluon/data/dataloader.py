"""DataLoader (parity: python/mxnet/gluon/data/dataloader.py).

TPU-native design: decode/augment on host (optionally in worker processes,
like the reference's _MultiWorkerIter over multiprocessing), batchify to
numpy, then a background prefetch thread keeps a bounded queue of ready
batches and (optionally) stages them onto device ahead of the consumer —
replacing the reference's C++ PrefetcherIter double buffer
(src/io/iter_prefetcher.h) with an equivalent host-thread pipeline that
overlaps input processing with TPU compute via JAX async dispatch.
"""

import contextlib
import multiprocessing
import os
import queue as _queue
import threading

import numpy as np

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (recursively for tuples/lists/dicts)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    if isinstance(data[0], dict):
        return {k: default_batchify_fn([d[k] for d in data]) for k in data[0]}
    data = np.asarray(data)
    return data


# Worker processes return numpy (cheap to pickle); conversion to device
# arrays happens in the main process during prefetch.
def default_mp_batchify_fn(data):
    if isinstance(data[0], NDArray):
        return np.stack([d.asnumpy() for d in data], axis=0)
    if isinstance(data[0], (tuple, list)):
        data = zip(*data)
        return [default_mp_batchify_fn(i) for i in data]
    if isinstance(data[0], dict):
        return {k: default_mp_batchify_fn([d[k] for d in data]) for k in data[0]}
    return np.asarray(data)


_worker_dataset = None
_worker_batchify = None

_pool_ctx_lock = threading.Lock()
_pool_ctx = None

_SANITIZE_ENV = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}


@contextlib.contextmanager
def _sanitized_env():
    """Temporarily pin the env keys that make a child interpreter skip the
    TPU plugin (sitecustomize register() is keyed on PALLAS_AXON_POOL_IPS)
    and use host CPU for any incidental jax work.  Callers hold
    _pool_ctx_lock, so the mutate-restore window is serialized."""
    saved = {k: os.environ.get(k) for k in _SANITIZE_ENV}
    os.environ.update(_SANITIZE_ENV)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _get_pool_context():
    """multiprocessing context for worker pools, created once.

    forkserver (not fork): forking a process whose JAX runtime has live
    threads deadlocks (JAX warns on os.fork); the forkserver parent is
    launched clean, so workers never inherit JAX state.  The forkserver is
    started HERE, exactly once, under the sanitized env — all future
    workers fork from it and inherit that env, so pool creation never
    mutates the parent env again (the round-2 mutate-restore around every
    Pool() raced concurrent jax importers).  If some other library already
    started the forkserver with the live TPU env, starting it again can't
    fix its env — fall back to spawn, whose children re-read the parent
    env at spawn time (sanitized per-pool in _make_worker_pool).
    """
    global _pool_ctx
    with _pool_ctx_lock:
        if _pool_ctx is not None:
            return _pool_ctx
        methods = multiprocessing.get_all_start_methods()
        if "forkserver" in methods:
            from multiprocessing import forkserver as _fs
            already = getattr(_fs._forkserver, "_forkserver_pid",
                              None) is not None
            if not already:
                with _sanitized_env():
                    _fs._forkserver.ensure_running()
                _pool_ctx = ("forkserver",
                             multiprocessing.get_context("forkserver"))
                return _pool_ctx
        _pool_ctx = ("spawn", multiprocessing.get_context("spawn"))
        return _pool_ctx


def _make_worker_pool(num_workers, initializer, initargs):
    method, ctx = _get_pool_context()
    if method == "forkserver":  # env pinned in the forkserver: no mutation
        return ctx.Pool(num_workers, initializer=initializer,
                        initargs=initargs)
    # spawn: children re-read env at spawn time, so a sanitized window is
    # unavoidable — serialized under the lock to keep it race-free.
    with _pool_ctx_lock, _sanitized_env():
        return ctx.Pool(num_workers, initializer=initializer,
                        initargs=initargs)


def _worker_init(dataset, batchify_fn):
    """Process-pool initializer: each fork-worker gets its own copy of the
    dataset in its own process globals."""
    global _worker_dataset, _worker_batchify
    _worker_dataset = dataset
    _worker_batchify = batchify_fn


_SHM_MIN_BYTES = 1 << 20  # arrays below 1 MiB just pickle


def _to_shared(obj):
    """Large numpy arrays → POSIX shared-memory handles, so worker batches
    cross the process boundary by page mapping instead of pickle bytes
    (parity: the reference's shared-mem NDArray worker transport,
    gluon/data/dataloader.py _as_in_context/shared_mem pipes).  Measured
    ~9x pipeline throughput at 224px float batches (PERF.md)."""
    if (isinstance(obj, np.ndarray) and obj.nbytes >= _SHM_MIN_BYTES
            and not obj.dtype.hasobject):
        from multiprocessing import shared_memory
        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        view = np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
        view[...] = obj
        name = shm.name
        shm.close()  # parent reopens by name and unlinks
        # ship the dtype OBJECT (str() mangles structured dtypes)
        return ("__shm__", name, obj.shape, obj.dtype)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_shared(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_shared(v) for k, v in obj.items()}
    return obj


def _from_shared(obj):
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        from multiprocessing import shared_memory
        _, name, shape, dtype = obj
        shm = shared_memory.SharedMemory(name=name)
        try:
            # one copy out of the mapping: a zero-copy view would pin the
            # segment via exported buffers and SharedMemory.close() then
            # raises BufferError at GC — the copy (~30ms for a 77MB batch)
            # buys deterministic unlink
            arr = np.ndarray(shape, np.dtype(dtype),
                             buffer=shm.buf).copy()
        finally:
            shm.close()
            shm.unlink()
        return arr
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_shared(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _from_shared(v) for k, v in obj.items()}
    return obj


def _worker_fn(samples):
    return _to_shared(_worker_batchify(
        [_worker_dataset[i] for i in samples]))


def _thread_worker_fn(dataset, batchify_fn, samples):
    """Thread-pool task: dataset passed explicitly — threads share the
    parent's globals, so per-loader state must not live there."""
    return batchify_fn([dataset[i] for i in samples])


def _as_device(data, pin_device):
    """Move a batchified (possibly nested) numpy batch onto device."""
    if isinstance(data, (list, tuple)):
        return type(data)(_as_device(d, pin_device) for d in data)
    if isinstance(data, dict):
        return {k: _as_device(v, pin_device) for k, v in data.items()}
    if isinstance(data, NDArray):
        return data
    return nd.array(data)


class _PrefetchIter:
    """Background thread pulls batches from `source_iter`, converts to
    device arrays, and keeps up to `prefetch` ready ahead of the consumer."""

    _SENTINEL = object()

    def __init__(self, source_iter, prefetch, pin_memory):
        self._queue = _queue.Queue(maxsize=max(1, prefetch))
        self._pin = pin_memory
        self._exc = None
        self._closed = threading.Event()

        def _put(item):
            # bounded put that gives up when the consumer abandoned us
            while not self._closed.is_set():
                try:
                    self._queue.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def _run():
            try:
                for batch in source_iter:
                    if not _put(_as_device(batch, pin_memory)):
                        break  # consumer gone; stop staging batches
            except Exception as e:  # propagate to consumer thread
                self._exc = e
            finally:
                # close the generator from ITS OWN consuming thread so its
                # cleanup (in-flight shm drain) runs deterministically
                close = getattr(source_iter, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
                _put(self._SENTINEL)

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()

    def close(self):
        self._closed.set()

    def __del__(self):
        self._closed.set()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is self._SENTINEL:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item


class DataLoader:
    """Loads batches from a Dataset.

    Parameters mirror the reference: dataset, batch_size, shuffle, sampler,
    last_batch, batch_sampler, batchify_fn, num_workers, pin_memory,
    prefetch, thread_pool.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, 2 * self._num_workers if prefetch is None
                             else prefetch)

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler

        if batchify_fn is None:
            self._batchify_fn = (default_mp_batchify_fn if self._num_workers
                                 else default_batchify_fn)
        else:
            self._batchify_fn = batchify_fn

        self._pool = None
        if self._num_workers > 0:
            if thread_pool:
                from multiprocessing.pool import ThreadPool
                self._pool = ThreadPool(self._num_workers)
            else:
                self._pool = _make_worker_pool(
                    self._num_workers, _worker_init,
                    (self._dataset, self._batchify_fn))

    def _single_process_iter(self):
        for batch_idx in self._batch_sampler:
            yield self._batchify_fn([self._dataset[i] for i in batch_idx])

    def _submit(self, batch_idx):
        if self._thread_pool:
            return self._pool.apply_async(
                _thread_worker_fn,
                (self._dataset, self._batchify_fn, batch_idx))
        return self._pool.apply_async(_worker_fn, (batch_idx,))

    def _multi_worker_iter(self):
        # keep up to prefetch async results in flight, in order
        it = iter(self._batch_sampler)
        pending = []
        try:
            for _ in range(max(1, self._prefetch)):
                pending.append(self._submit(next(it)))
        except StopIteration:
            pass
        try:
            while pending:
                res = pending.pop(0)
                try:
                    pending.append(self._submit(next(it)))
                except StopIteration:
                    pass
                out = res.get()
                yield _from_shared(out) if not self._thread_pool else out
        finally:
            # consumer abandoned us: claim EVERY in-flight result so its
            # shared-memory segments are unlinked, not leaked.  A slow
            # batch (>1s decode) must not abort the drain — later results
            # may already be sitting complete (continue, don't break); but
            # a terminated pool (GC finalization order is arbitrary) never
            # completes anything, so stop once the pool is known dead.
            pool_alive = not self._thread_pool
            for res in pending:
                while pool_alive:
                    try:
                        _from_shared(res.get(timeout=5))
                        break
                    except multiprocessing.TimeoutError:
                        if getattr(self._pool, "_state", "RUN") != "RUN":
                            pool_alive = False  # dead: nothing completes
                    except Exception:
                        break  # worker error: no segment was shipped

    def __iter__(self):
        source = (self._multi_worker_iter() if self._pool is not None
                  else self._single_process_iter())
        return iter(_PrefetchIter(source, prefetch=max(1, self._prefetch),
                                  pin_memory=self._pin_memory))

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        if self._pool is not None:
            self._pool.terminate()
