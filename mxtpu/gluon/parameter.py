"""Neural-network parameters (parity: python/mxnet/gluon/parameter.py).

Reference semantics kept: a Parameter owns one NDArray copy per context,
deferred initialization via unknown (0) shape dims resolved at first forward,
grad_req in {write, add, null}, and ParameterDict with prefix-scoped names.

TPU-native deltas: per-ctx copies are per-*device* jax arrays; under a mesh
the canonical copy is a sharded global array (set by mxtpu.parallel); grads
live beside data and are attached to the autograd tape exactly like NDArray
leaves.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import jax.numpy as jnp
import numpy as onp

from ..base import MXTPUError
from ..context import Context, current_context, cpu
from ..ndarray import NDArray
from .. import autograd, initializer

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXTPUError):
    """Error for unfinished deferred initialization (parity: same name)."""


def _shape_known(shape) -> bool:
    return shape is not None and all(s > 0 for s in shape)


class Parameter:
    """A Block parameter (parity: gluon.Parameter).

    Supports deferred init: any 0 in ``shape`` means "infer at first
    forward"; layers call ``_finish_deferred_init`` once shapes are known
    (mirrors the reference's _finish_deferred_init driven by infer_shape).
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self._var = None
        self._data = None          # list[NDArray] aligned with self._ctx_list
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._grad_req = None
        self.grad_req = grad_req
        # row_sparse grad: gradients ACCUMULATE densely (XLA scatter-add is
        # the TPU fast path) but are EXPOSED sparsely — grad() compacts to
        # the touched rows recorded by the producing layer (Embedding
        # sparse_grad), and the SGD update applies only those rows.
        self._grad_stype = grad_stype
        self._sparse_row_ids = None
        if stype != "default":
            import warnings
            warnings.warn("sparse parameter stype is dense-backed in mxtpu "
                          "(row_sparse grads ARE supported; SURVEY.md §7)")
        if grad_stype not in ("default", "row_sparse"):
            raise ValueError(f"unsupported grad_stype {grad_stype!r}")

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    # -- grad_req ---------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError(f"grad_req must be write/add/null, got {req}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                for d in self._data:
                    d._grad = None
                    d._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape) if new_shape is not None else None
            return
        if new_shape is None:
            return
        unknown_ok = len(self._shape) == len(new_shape) and all(
            s == 0 or s == n for s, n in zip(self._shape, new_shape))
        if not unknown_ok:
            raise AssertionError(
                f"Expected shape {new_shape} is incompatible with given "
                f"shape {self._shape} for Parameter {self.name}")
        self._shape = tuple(new_shape)

    # -- init -------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Materialize (or defer) this parameter on the given context(s)."""
        if default_init is None:
            default_init = initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        if not _shape_known(self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(
                f"Cannot initialize Parameter {self.name} because it has "
                f"invalid shape: {self._shape}. Please specify in_units/"
                "in_channels/etc for the layer or set allow_deferred_init.")
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        if not _shape_known(self._shape):
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape {self._shape}; "
                "run a forward pass or call infer_shape first")
        with autograd.pause():
            if data is None:
                data = NDArray(jnp.zeros(self._shape, jnp.dtype(self.dtype)))
                initializer.create(init if init is not None else default_init)(
                    initializer.InitDesc(self.name), data)
            if str(data.dtype) != str(self.dtype):
                # initializers fill in fp32; honor a cast() that happened
                # before the deferred init resolved
                data = NDArray(data.data.astype(jnp.dtype(self.dtype)))
            self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._ctx_list = list(ctx_list)
        self._data = [data.as_in_context(c).copy() if i else
                      data.as_in_context(ctx_list[0])
                      for i, c in enumerate(self._ctx_list)]
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = [NDArray(jnp.zeros(d.shape, d.data.dtype))
                      for d in self._data]
        for d, g in zip(self._data, self._grad):
            d._grad = g
            d._grad_req = self._grad_req

    # -- access -----------------------------------------------------------
    def _check_and_get(self, arr_list, ctx):
        if arr_list is not None:
            if ctx is list:
                return arr_list
            if ctx is None:
                if len(arr_list) == 1:
                    return arr_list[0]
                ctx = current_context()
            for c, a in zip(self._ctx_list, arr_list):
                if c == ctx:
                    return a
            # a mesh-sharded parameter serves every device in its mesh
            # (SPMD path: there is one logical copy, XLA owns placement)
            if len(arr_list) == 1 and arr_list[0].is_sharded:
                return arr_list[0]
            raise MXTPUError(
                f"Parameter {self.name} was not initialized on context {ctx}; "
                f"it is on {self._ctx_list}")
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter {self.name} has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass.")
        raise MXTPUError(
            f"Parameter {self.name} has not been initialized. You should "
            "initialize parameters and create Trainer with Block.collect_params() "
            "instead of Block.params")

    def data(self, ctx=None) -> NDArray:
        return self._check_and_get(self._data, ctx)

    def list_data(self) -> List[NDArray]:
        return self._check_and_get(self._data, list)

    def grad(self, ctx=None) -> NDArray:
        if self._data is not None and self._grad is None:
            raise MXTPUError(
                f"Cannot get gradient array for Parameter {self.name} "
                "because grad_req='null'")
        g = self._check_and_get(self._grad, ctx)
        return self._sparsify_grad(g)

    def list_grad(self) -> List[NDArray]:
        if self._data is not None and self._grad is None:
            raise MXTPUError(
                f"Cannot get gradient array for Parameter {self.name} "
                "because grad_req='null'")
        return [self._sparsify_grad(g)
                for g in self._check_and_get(self._grad, list)]

    def _accumulate_sparse_row_ids(self, ids):
        """Union newly touched rows into the pending id set (called by the
        producing layer on every recorded eager forward; consumed —
        reset — by the optimizer step / zero_grad)."""
        import jax.numpy as jnp
        if self._sparse_row_ids is None:
            self._sparse_row_ids = NDArray(jnp.asarray(ids, jnp.int32))
        else:
            self._sparse_row_ids = NDArray(jnp.union1d(
                self._sparse_row_ids.data, jnp.asarray(ids, jnp.int32)))

    def _consume_sparse_row_ids(self):
        self._sparse_row_ids = None

    def _sparsify_grad(self, g):
        """row_sparse grad view: compact the dense buffer onto the union
        of rows touched since the last consume (exact — untouched rows
        accumulated zero).  With no recorded ids (e.g. hybridized forward:
        tracing records none) the dense buffer is returned — always
        exact, just not compact."""
        if self._grad_stype != "row_sparse" or self._sparse_row_ids is None:
            return g
        from ..ndarray.sparse import RowSparseNDArray
        import jax.numpy as jnp
        ids_j = self._sparse_row_ids.data
        vals = jnp.take(g.data, ids_j, axis=0)
        return RowSparseNDArray(NDArray(vals), NDArray(ids_j), g.shape)

    def _list_dense_grad(self):
        """Dense grad buffers for kvstore allreduce (the reduced result is
        written back in place; sparse views are re-derived afterwards)."""
        return self._check_and_get(self._grad, list)

    def list_ctx(self) -> List[Context]:
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise MXTPUError(
                f"Parameter {self.name} has not been initialized")
        return self._ctx_list

    def set_data(self, data):
        """Set value on all contexts (parity: Parameter.set_data)."""
        self.shape = tuple(data.shape)
        if self._data is None:
            if not self._deferred_init:
                raise MXTPUError(
                    f"Parameter {self.name} has not been initialized")
            init, ctx, default_init, _ = self._deferred_init
            if not isinstance(data, NDArray):
                data = NDArray(jnp.asarray(data))
            self._deferred_init = (init, ctx, default_init, data)
            return
        src = data.data if isinstance(data, NDArray) else jnp.asarray(data)
        for d in self._data:
            d._rebind(jnp.asarray(src, d.data.dtype))

    def zero_grad(self):
        if self._grad is None:
            return
        self._consume_sparse_row_ids()
        for g in self._grad:
            g._rebind(jnp.zeros(g.shape, g.data.dtype))

    def reset_ctx(self, ctx):
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data:
            data = self._data[0]
            with autograd.pause():
                self._init_impl(data, ctx)
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise MXTPUError(
                f"Cannot reset context for Parameter {self.name} because it "
                "has not been initialized")

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        with autograd.pause():
            self._data = [NDArray(d.data.astype(jnp.dtype(dtype)))
                          for d in self._data]
            if self._grad is not None:
                self._grad = [NDArray(g.data.astype(jnp.dtype(dtype)))
                              for g in self._grad]
                for d, g in zip(self._data, self._grad):
                    d._grad = g
                    d._grad_req = self._grad_req

    def var(self):
        """Symbolic variable for this parameter (parity: Parameter.var)."""
        if self._var is None:
            from .. import symbol
            self._var = symbol.var(self.name, shape=self.shape,
                                   dtype=self.dtype,
                                   lr_mult=self.lr_mult,
                                   wd_mult=self.wd_mult)
        return self._var

    # sparse API kept for surface parity; dense behavior
    def row_sparse_data(self, row_id):
        return self.data()

    def list_row_sparse_data(self, row_id):
        return self.list_data()


class Constant(Parameter):
    """Non-updating parameter (parity: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(jnp.asarray(onp.asarray(value, dtype=onp.float32)))
        self.value = value

        class Init(initializer.Initializer):
            def _init_weight(_, desc, arr):
                arr._rebind(jnp.asarray(value.data, arr.data.dtype))

        init_name = f"Constant_{name}_{id(self)}"
        initializer._INIT_REGISTRY[init_name.lower()] = Init
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.data.dtype), init=init_name,
                         differentiable=False)


class ParameterDict:
    """Prefix-scoped dict of Parameters (parity: gluon.ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self.values())
        return f"{type(self).__name__} '{self._prefix}' (\n{s}\n)"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __len__(self):
        return len(self._params)

    def __contains__(self, key):
        return key in self._params

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Get or create parameter ``prefix+name`` (parity: get)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        # merge partial shapes (parity: shape unification)
                        v = tuple(v)
                        if len(v) == len(existing):
                            merged = tuple(
                                e if e else n for e, n in zip(existing, v))
                            ok = all(e == 0 or n == 0 or e == n
                                     for e, n in zip(existing, v))
                            if not ok:
                                raise AssertionError(
                                    f"Cannot retrieve Parameter {name} "
                                    f"because shapes mismatch: {existing} vs {v}")
                            param._shape = merged
                            continue
                    if v is not None and v != existing and k != "init":
                        raise AssertionError(
                            f"Cannot retrieve Parameter {name} because "
                            f"attribute {k} mismatch: {existing} vs {v}")
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXTPUError(
                    f"No constant named {name}; provide value=")
            param = Constant(name, value)
            self._params[name] = param
        elif value is not None:
            if not isinstance(param, Constant):
                raise MXTPUError(f"Parameter {name} exists but is not a Constant")
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                if self._params[k] is not v:
                    raise ValueError(
                        f"Cannot update self with other because they have "
                        f"different Parameters with the same name {k}")
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def list_ctx(self):
        assert self._params, "ParameterDict is empty"
        block = set()
        for v in self.values():
            try:
                for c in v.list_ctx():
                    block.add(c)
            except MXTPUError:
                pass
        return sorted(block, key=str)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        """Save to the NDArray name→array container format
        (parity: ParameterDict.save → .params file)."""
        from ..ndarray import serialization

        arg_dict = {}
        for param in self.values():
            weight = param._reduce() if hasattr(param, "_reduce") else (
                param.data().asnumpy() if param._data else None)
            if weight is None:
                continue
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    f"Prefix {strip_prefix} is to be striped before saving, "
                    f"but Parameter {param.name} does not start with it")
            arg_dict[param.name[len(strip_prefix):]] = NDArray(
                jnp.asarray(weight))
        serialization.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import serialization

        loaded = serialization.load(filename)
        if isinstance(loaded, dict):
            arg_dict = {restore_prefix + k.replace("arg:", "").replace(
                "aux:", ""): v for k, v in loaded.items()}
        else:
            raise MXTPUError(f"{filename} does not contain a name→array dict")
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise MXTPUError(
                        f"Parameter {name} is missing in file {filename}")
        for name in arg_dict:
            if name not in self._params:
                if ignore_extra:
                    continue
                raise MXTPUError(
                    f"Parameter {name} loaded from file {filename} is not "
                    "present in this ParameterDict")
            self[name]._load_init(arg_dict[name], ctx)


def _param_load_init(self, data, ctx):
    """Parameter._load_init (parity): set data, honoring deferred state."""
    if self._shape is not None:
        unknown_ok = len(self._shape) == len(data.shape) and all(
            s == 0 or s == d for s, d in zip(self._shape, data.shape))
        if not unknown_ok:
            raise MXTPUError(
                f"Failed loading Parameter {self.name} from saved params: "
                f"shape incompatible expected {self._shape} vs saved "
                f"{tuple(data.shape)}")
        self._shape = tuple(data.shape)
    if self.dtype is not None and jnp.dtype(self.dtype) != data.data.dtype:
        data = NDArray(data.data.astype(jnp.dtype(self.dtype)))
    if ctx is None:
        ctx = [current_context()]
    if isinstance(ctx, Context):
        ctx = [ctx]
    if self._data is None:
        if self._deferred_init:
            ctx = self._deferred_init[1]
        self._init_impl(data, ctx)
        self._deferred_init = ()
    else:
        self.set_data(data)


Parameter._load_init = _param_load_init
