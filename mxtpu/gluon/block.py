"""Block / HybridBlock (parity: python/mxnet/gluon/block.py).

Block is the imperative NN container: child registry via ``__setattr__``,
prefix/name scopes, parameter collection, save/load, hooks.  HybridBlock adds
``hybridize()`` — in the reference this traces ``hybrid_forward`` to a Symbol
graph executed by CachedOp (src/imperative/cached_op.cc); here it
functionalizes the block over its parameter pytree and hands it to
``jax.jit`` via mxtpu.cached_op.CachedOp.  `static_alloc`/`static_shape`
flags are accepted: XLA always plans memory statically, so they are
documented no-ops rather than modes.

Divergence note (deferred shape inference, SURVEY §7 hard-part 2): the
reference resolves unknown param shapes with symbolic whole-graph shape
inference; here every built-in layer overrides ``infer_shape`` to infer its
own param shapes from the input, which covers the model zoo.  Custom blocks
with deferred-shape params must override ``infer_shape`` (a clear error says
so).
"""

from __future__ import annotations

import copy
import re
import threading
import warnings
from collections import OrderedDict

import jax.numpy as jnp
import numpy as onp

from .. import autograd, ndarray
from ..base import MXTPUError
from ..context import Context, current_context
from ..ndarray import NDArray
from .parameter import (Parameter, ParameterDict, DeferredInitializationError,
                        Constant)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name scope for automatic prefixes (parity: _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        """Create prefix and params for new Block."""
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                if not hasattr(_NameManager._current, "value"):
                    _NameManager._current.value = _NameManager()
                prefix = _NameManager._current.value.get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        self._name_scope = _name_prefix_scope(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        self._name_scope.__exit__(ptype, value, trace)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope


class _NameManager:
    """Global name counter (parity: mxnet.name.NameManager)."""

    _current = threading.local()

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = f"{hint}{self._counter[hint]}"
        self._counter[hint] += 1
        return name


class _name_prefix_scope:
    def __init__(self, prefix):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *a):
        pass


class Block:
    """Base class for all neural network layers and models
    (parity: gluon.Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else (
            self._prefix)
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {_indent(repr(block), 2)}"
            for key, block in self.__dict__.items()
            if isinstance(block, Block))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(
                    value, type(existing)):
                raise TypeError(
                    f"Changing attribute type for {self.name} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, (
                    "Overriding Parameter attribute %s is not allowed. "
                    "If you want to share parameters between blocks, please "
                    "set 'params' at Block construction instead." % name)
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """Prefix-scope context manager (parity: Block.name_scope)."""
        return self._scope

    @property
    def params(self):
        """This block's own ParameterDict (no children)."""
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        """All parameters of self and children (parity: collect_params)."""
        self._check_container_with_block()
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for cld in self._children.values():
            ret.update(cld.collect_params(select=select))
        return ret

    def _check_container_with_block(self):
        children = set(self._children.values())
        for k, v in self.__dict__.items():
            if isinstance(v, (list, tuple, dict)) and k != "_children":
                leaves = v.values() if isinstance(v, dict) else v
                if any(isinstance(i, Block) and i not in children
                       for i in leaves):
                    warnings.warn(
                        f'"{k}" is an unregistered container with Blocks. '
                        "Note that Blocks inside the list, tuple or dict will "
                        "not be registered automatically. Make sure to "
                        "register them using register_child() or switching "
                        "to nn.Sequential/nn.HybridSequential instead.",
                        stacklevel=3)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        """Save parameters with structural names (parity: save_parameters)."""
        from ..ndarray import serialization

        params = self._collect_params_with_prefix()
        if deduplicate:
            reverse = {}
            for k, v in params.items():
                reverse.setdefault(id(v), []).append(k)
            params = {ks[0]: params[ks[0]].data() if params[ks[0]]._data
                      else None for ks in reverse.values()}
            params = {k: v for k, v in params.items() if v is not None}
        else:
            params = {k: v.data() for k, v in params.items()
                      if v._data is not None}
        serialization.save(filename, params)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        """Load parameters saved by save_parameters (parity)."""
        from ..ndarray import serialization

        loaded = serialization.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        # detect full-name format (ParameterDict.save / export) vs structural
        if not any("." in k for k in loaded.keys()) and any(
                k.startswith(self.prefix) for k in loaded.keys()):
            # parameter-name keyed: strip prefix and route via collect_params
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix)
            return
        if not allow_missing:
            for name in params.keys():
                if name not in loaded:
                    raise MXTPUError(
                        f"Parameter '{name}' is missing in file '{filename}', "
                        "which contains parameters: %s" % _brief_print(loaded))
        for name in loaded:
            if name not in params:
                if ignore_extra:
                    continue
                raise MXTPUError(
                    f"Parameter '{name}' loaded from file '{filename}' is "
                    "not present in this Block")
            value = loaded[name]
            if cast_dtype:
                if dtype_source == "current" and params[name].dtype:
                    value = NDArray(value.data.astype(
                        jnp.dtype(params[name].dtype)))
                elif dtype_source == "saved":
                    params[name].dtype = str(value.data.dtype)
            params[name]._load_init(value, ctx)

    # legacy names kept (parity: deprecated save_params/load_params)
    def save_params(self, filename):
        warnings.warn("save_params is deprecated. Please use save_parameters.")
        self.save_parameters(filename)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        warnings.warn("load_params is deprecated. Please use load_parameters.")
        self.load_parameters(filename, ctx, allow_missing, ignore_extra)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks)
        self._forward_hooks[handle.id] = hook
        return handle

    def apply(self, fn):
        """Apply fn recursively to self and children (parity: apply)."""
        for cld in self._children.values():
            cld.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as _init

        self.collect_params().initialize(
            init or _init.Uniform(), ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        """Activate compiled execution for HybridBlock children."""
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print per-layer output shapes and param counts (parity: summary)."""
        summary = OrderedDict()
        hooks = []

        def _register(block):
            def _hook(blk, inp, out):
                name = f"{blk.__class__.__name__}-{len(summary) + 1}"
                entry = OrderedDict()
                out0 = out[0] if isinstance(out, (list, tuple)) else out
                entry["output_shape"] = tuple(out0.shape)
                n_params = 0
                for p in blk.params.values():
                    if p._data is not None:
                        n_params += int(onp.prod(p.shape))
                entry["n_params"] = n_params
                summary[name] = entry

            hooks.append(block.register_forward_hook(_hook))

        self.apply(_register)
        try:
            self(*inputs)
            print("-" * 64)
            print(f"{'Layer':<32}{'Output Shape':<20}{'Params':<12}")
            print("=" * 64)
            total = 0
            for name, entry in summary.items():
                print(f"{name:<32}{str(entry['output_shape']):<20}"
                      f"{entry['n_params']:<12}")
                total += entry["n_params"]
            print("=" * 64)
            print(f"Total params: {total}")
            print("-" * 64)
        finally:
            for h in hooks:
                h.detach()


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks_dict):
        self._hooks_dict = hooks_dict
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1

    def detach(self):
        self._hooks_dict.pop(self.id, None)


class HybridBlock(Block):
    """Block with a compilable forward (parity: gluon.HybridBlock).

    Subclasses implement ``hybrid_forward(self, F, x, *args, **params)``
    where F is the op namespace (mxtpu.ndarray imperatively; also
    mxtpu.ndarray under jit trace — NDArrays then carry tracers) and params
    arrive as keyword arrays, exactly like the reference.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_op = None

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._clear_cached_op()
        # children run inside the parent's compiled graph; they do NOT build
        # their own CachedOps (parity: only the outermost call is cached)
        for cld in self._children.values():
            cld.hybridize(False, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)
        if active:
            self._active = True

    def _clear_cached_op(self):
        self._cached_op = None

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Infer deferred parameter shapes from inputs.

        Built-in layers override this; custom blocks with deferred-shape
        parameters must too (divergence from the reference's symbolic
        whole-graph inference — see module docstring)."""
        if any(p._deferred_init for p in self._reg_params.values()):
            raise MXTPUError(
                f"{type(self).__name__} has deferred-shape parameters but "
                "does not override infer_shape(); specify full shapes "
                "(in_units/in_channels) or implement infer_shape")

    def infer_type(self, *args):
        pass

    def _deferred_infer_and_init(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init:
                p._finish_deferred_init()

    def _get_param_arrays(self, ctx):
        try:
            return {name: p.data(ctx)
                    for name, p in self._reg_params.items()
                    if not name.startswith("_")}
        except DeferredInitializationError:
            raise

    def forward(self, x, *args):
        """Dispatch: cached-op path when hybridized, imperative otherwise."""
        if not isinstance(x, NDArray):
            import numpy as _onp
            if isinstance(x, (_onp.ndarray, _onp.generic)):
                x = ndarray.array(x)
            else:
                from ..symbol import Symbol
                if isinstance(x, Symbol):
                    return self._symbolic_forward(x, *args)
                raise TypeError(
                    f"HybridBlock input must be NDArray, got {type(x)}")
        if args and any(isinstance(a, _np_types()) for a in args):
            args = tuple(ndarray.array(a) if isinstance(a, _np_types())
                         else a for a in args)
        if self._active:
            if self._cached_op is None:
                from ..cached_op import CachedOp
                self._cached_op = CachedOp(self, self._flags)
            return self._cached_op(x, *args)
        return self._imperative_forward(x, *args)

    def _imperative_forward(self, x, *args):
        """The un-cached forward path (also the trace body under jit)."""
        ctx = x.context
        try:
            params = self._get_param_arrays(ctx)
        except DeferredInitializationError:
            self._deferred_infer_and_init(x, *args)
            params = self._get_param_arrays(ctx)
        return self.hybrid_forward(ndarray, x, *args, **params)

    def _symbolic_forward(self, x, *args):
        from .. import symbol
        params = {name: p.var() for name, p in self._reg_params.items()
                  if not name.startswith("_")}
        return self.hybrid_forward(symbol, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0):
        """Export model to ``path-symbol.json`` + ``path-%04d.params``
        (parity: HybridBlock.export; loadable by SymbolBlock.imports)."""
        from ..cached_op import export_block
        return export_block(self, path, epoch)

    def optimize_for(self, x, *args, backend=None, **kwargs):
        # subgraph backends (oneDNN/TRT) have no TPU analogue; XLA is the
        # whole-graph compiler. Accept and hybridize.
        self.hybridize(True)
        return self(x, *args)


class SymbolBlock(HybridBlock):
    """Build a block from a saved symbolic graph (parity: gluon.SymbolBlock).

    Construct via SymbolBlock.imports(symbol_file, input_names, param_file).
    The jaxpr-backed symbol program replays through mxtpu.symbol.
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=None)
        from .. import symbol as _sym

        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._sym_outputs = outputs
        self._sym_inputs = inputs
        input_names = {i.name for i in inputs}
        # register every non-input graph argument as a parameter
        for name in outputs.list_arguments():
            if name not in input_names:
                p = Parameter(name, allow_deferred_init=True)
                self._params._params[name] = p
        for name in outputs.list_auxiliary_states():
            p = Parameter(name, grad_req="null", allow_deferred_init=True)
            self._params._params[name] = p
        if params is not None:
            for name, arr in params.items():
                clean = name.replace("arg:", "").replace("aux:", "")
                if clean in self._params:
                    p = self._params[clean]
                    # adopt the stored dtype — int8 quantized weights
                    # must NOT be silently upcast to the fp32 default
                    p.dtype = arr.dtype
                    p._load_init(arr, None)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as _sym
        from ..ndarray import serialization

        sym = _sym.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [_sym.var(n) for n in input_names]
        params = serialization.load(param_file) if param_file else None
        ret = SymbolBlock(sym, inputs, params)
        if ctx is not None:
            ret.collect_params().reset_ctx(ctx)
        return ret

    def forward(self, x, *args):
        from .. import symbol as _sym

        args_map = {}
        for inp, val in zip(self._sym_inputs, (x,) + args):
            args_map[inp.name] = val
        for name, p in self._params.items():
            if p._data is not None:
                args_map[name] = p.data(x.context)
        outs = self._sym_outputs.eval(**args_map)
        if isinstance(outs, (list, tuple)) and len(outs) == 1:
            return outs[0]  # single-output symbols yield one NDArray
        return outs

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError  # forward is overridden


def _indent(s, num_spaces):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


def _brief_print(d):
    keys = sorted(d.keys())
    if len(keys) > 10:
        keys = keys[:10] + ["..."]
    return ", ".join(keys)


def _np_types():
    import numpy as _onp
    return (_onp.ndarray, _onp.generic)
