"""Contrib layers (parity: python/mxnet/gluon/contrib/nn/basic_layers.py).

SyncBatchNorm note: the reference synchronized batch stats across GPUs with
a dedicated kernel (src/operator/contrib/sync_batch_norm.cc). Under SPMD
execution here, activations are GLOBAL arrays over the mesh — BatchNorm's
batch statistics already reduce over the full global batch (XLA inserts the
collectives) — so SyncBatchNorm IS BatchNorm; the class exists for API
parity and documents the equivalence.
"""

from __future__ import annotations

import warnings

from ... import nn
from ...block import HybridBlock
from ....base import MXTPUError

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(nn.Sequential):
    """Parallel branches, outputs concatenated (parity: contrib.Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(nn.HybridSequential):
    """(parity: contrib.HybridConcurrent)"""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """(parity: contrib.Identity)"""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(nn.Embedding):
    """Sparse-gradient embedding (parity: contrib.SparseEmbedding —
    simply Embedding with sparse_grad=True since the row-sparse path
    landed: backward produces a RowSparseNDArray gradient and optimizers
    apply lazy row-wise updates)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


class SyncBatchNorm(nn.BatchNorm):
    """Cross-device BatchNorm (parity: contrib.SyncBatchNorm — see module
    docstring: under SPMD the plain BatchNorm already reduces globally)."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=(
                             running_variance_initializer),
                         in_channels=in_channels, **kwargs)


class _PixelShuffle(HybridBlock):
    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor,) * ndim
        self._factor = tuple(factor)
        self._ndim = ndim

    def hybrid_forward(self, F, x):
        f = self._factor
        if self._ndim == 1:
            B, C, W = x.shape
            c = C // f[0]
            x = F.reshape(x, shape=(B, c, f[0], W))
            x = F.transpose(x, (0, 1, 3, 2))
            return F.reshape(x, shape=(B, c, W * f[0]))
        if self._ndim == 2:
            B, C, H, W = x.shape
            c = C // (f[0] * f[1])
            x = F.reshape(x, shape=(B, c, f[0], f[1], H, W))
            x = F.transpose(x, (0, 1, 4, 2, 5, 3))
            return F.reshape(x, shape=(B, c, H * f[0], W * f[1]))
        B, C, D, H, W = x.shape
        c = C // (f[0] * f[1] * f[2])
        x = F.reshape(x, shape=(B, c, f[0], f[1], f[2], D, H, W))
        x = F.transpose(x, (0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(B, c, D * f[0], H * f[1], W * f[2]))

    def __repr__(self):
        return "{}(factor={})".format(type(self).__name__, self._factor)


class PixelShuffle1D(_PixelShuffle):
    """(parity: contrib.PixelShuffle1D)"""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)


class PixelShuffle2D(_PixelShuffle):
    """(parity: contrib.PixelShuffle2D)"""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)


class PixelShuffle3D(_PixelShuffle):
    """(parity: contrib.PixelShuffle3D)"""

    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)
