"""Contrib NN blocks (parity: gluon/contrib/nn/basic_layers.py)."""

from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           SparseEmbedding, SyncBatchNorm, PixelShuffle1D,
                           PixelShuffle2D, PixelShuffle3D)
