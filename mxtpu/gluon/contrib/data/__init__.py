"""Contrib datasets (parity: python/mxnet/gluon/contrib/data/)."""

from . import text  # noqa: F401
