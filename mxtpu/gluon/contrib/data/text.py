"""Text datasets for language modelling (parity: python/mxnet/gluon/
contrib/data/text.py — WikiText-style corpus datasets.  The reference
downloads the archives; with zero egress these load the same file
formats from a local root, so real WikiText checkouts work unchanged).
"""

from __future__ import annotations

import io
import os

import numpy as np

from ...data import dataset as _dataset
from ....contrib.text.vocab import Vocabulary
from .... import ndarray as nd

__all__ = ["CorpusDataset", "WikiText2", "WikiText103"]


class CorpusDataset(_dataset.Dataset):
    """A flat token-id stream over a whitespace-tokenized text file,
    sliced into fixed-length sequences (parity: _LanguageModelDataset /
    CorpusDataset semantics: bos/eos insertion, vocabulary indexing,
    seq_len slicing with the ragged tail dropped)."""

    def __init__(self, filename, seq_len=35, vocab=None, bos=None,
                 eos="<eos>", encoding="utf8"):
        self._seq_len = int(seq_len)
        tokens = []
        with io.open(filename, "r", encoding=encoding) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                if bos is not None:
                    tokens.append(bos)
                tokens.extend(parts)
                if eos is not None:
                    tokens.append(eos)
        if vocab is None:
            import collections
            counter = collections.Counter(tokens)
            extra = [t for t in (bos, eos) if t is not None]
            vocab = Vocabulary(counter, reserved_tokens=extra or None)
        self._vocab = vocab
        ids = np.asarray(vocab.to_indices(tokens), np.int32)
        n = (len(ids) - 1) // self._seq_len  # -1: target is shifted by 1
        self._data = ids[:n * self._seq_len].reshape(n, self._seq_len)
        self._target = ids[1:n * self._seq_len + 1].reshape(
            n, self._seq_len)

    @property
    def vocabulary(self):
        return self._vocab

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return (nd.array(self._data[idx], dtype="int32"),
                nd.array(self._target[idx], dtype="int32"))


class _WikiText(CorpusDataset):
    # segment name → file name (WikiText checkouts call it "valid")
    _segments = {"train": "wiki.train.tokens", "val": "wiki.valid.tokens",
                 "valid": "wiki.valid.tokens", "test": "wiki.test.tokens"}

    def __init__(self, root, segment="train", seq_len=35, vocab=None):
        if segment not in self._segments:
            raise ValueError("segment must be one of %s"
                             % sorted(self._segments))
        seg_file = self._segments[segment]
        path = os.path.join(root, seg_file)
        if not os.path.exists(path):
            raise FileNotFoundError(
                "%s not found under %s — place a %s checkout there "
                "(no network access: the reference's auto-download is "
                "a documented divergence)" %
                (seg_file, root, type(self).__name__))
        super().__init__(path, seq_len=seq_len, vocab=vocab)


class WikiText2(_WikiText):
    """WikiText-2 from a local checkout (parity: contrib.data.text
    .WikiText2)."""


class WikiText103(_WikiText):
    """WikiText-103 from a local checkout (parity: contrib.data.text
    .WikiText103)."""
