"""Gluon contrib (parity: python/mxnet/gluon/contrib/)."""

from . import nn
from . import rnn
from . import estimator
from . import data
