"""Contrib RNN cells (parity: gluon/contrib/rnn/)."""

from .rnn_cell import VariationalDropoutCell, LSTMPCell
from .conv_rnn_cell import Conv1DRNNCell, Conv2DRNNCell, Conv1DLSTMCell, \
    Conv2DLSTMCell, Conv1DGRUCell, Conv2DGRUCell
