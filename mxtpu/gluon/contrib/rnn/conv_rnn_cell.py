"""Convolutional recurrent cells (parity: gluon/contrib/rnn/conv_rnn_cell.py
— ConvRNN/ConvLSTM/ConvGRU in 1D/2D)."""

from __future__ import annotations

from ....base import MXTPUError
from ...rnn.rnn_cell import HybridRecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv1DLSTMCell",
           "Conv2DLSTMCell", "Conv1DGRUCell", "Conv2DGRUCell"]


def _norm_tuple(v, ndim):
    if isinstance(v, int):
        return (v,) * ndim
    return tuple(v)


class _BaseConvRNNCell(HybridRecurrentCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, activation, num_gates, conv_ndim,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)  # (C, spatial...)
        self._hidden_channels = hidden_channels
        self._ndim = conv_ndim
        self._i2h_kernel = _norm_tuple(i2h_kernel, conv_ndim)
        self._h2h_kernel = _norm_tuple(h2h_kernel, conv_ndim)
        for k in self._h2h_kernel:
            assert k % 2 == 1, \
                "h2h kernel dims must be odd to preserve spatial size"
        self._i2h_pad = _norm_tuple(i2h_pad, conv_ndim)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        self._activation = activation
        self._num_gates = num_gates
        in_c = self._input_shape[0]
        oc = num_gates * hidden_channels
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(oc, in_c) + self._i2h_kernel,
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(oc, hidden_channels) + self._h2h_kernel,
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(oc,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(oc,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    @property
    def _state_shape(self):
        # spatial dims preserved by same-padding h2h; i2h must preserve too
        return (self._hidden_channels,) + self._input_shape[1:]

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NC" + "DHW"[-self._ndim:]}]

    def infer_shape(self, inputs, states):
        pass

    def _conv_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=self._num_gates *
                            self._hidden_channels)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=self._num_gates *
                            self._hidden_channels)
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, activation, conv_ndim, **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 1, conv_ndim,
                         **kwargs)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, activation, conv_ndim, **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 4, conv_ndim,
                         **kwargs)

    def _alias(self):
        return "conv_lstm"

    def state_info(self, batch_size=0):
        shape = (batch_size,) + self._state_shape
        layout = "NC" + "DHW"[-self._ndim:]
        return [{"shape": shape, "__layout__": layout},
                {"shape": shape, "__layout__": layout}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = F.split_v2(gates, 4, axis=1)
        in_gate = F.Activation(sl[0], act_type="sigmoid")
        forget_gate = F.Activation(sl[1], act_type="sigmoid")
        in_transform = self._get_activation(F, sl[2], self._activation)
        out_gate = F.Activation(sl[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, activation, conv_ndim, **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 3, conv_ndim,
                         **kwargs)

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_r, i2h_z, i2h_n = F.split_v2(i2h, 3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split_v2(h2h, 3, axis=1)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = self._get_activation(F, i2h_n + reset_gate * h2h_n,
                                          self._activation)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * states[0]
        return next_h, [next_h]


class Conv1DRNNCell(_ConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, i2h_pad=1, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 1, **kwargs)


class Conv2DRNNCell(_ConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(1, 1), activation="tanh",
                 **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 2, **kwargs)


class Conv1DLSTMCell(_ConvLSTMCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, i2h_pad=1, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 1, **kwargs)


class Conv2DLSTMCell(_ConvLSTMCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(1, 1), activation="tanh",
                 **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 2, **kwargs)


class Conv1DGRUCell(_ConvGRUCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, i2h_pad=1, activation="tanh", **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 1, **kwargs)


class Conv2DGRUCell(_ConvGRUCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(1, 1), activation="tanh",
                 **kwargs):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, 2, **kwargs)
