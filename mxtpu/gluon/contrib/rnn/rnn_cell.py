"""Contrib recurrent cells (parity: gluon/contrib/rnn/rnn_cell.py)."""

from __future__ import annotations

from ....base import MXTPUError
from ...rnn.rnn_cell import ModifierCell, HybridRecurrentCell, \
    BidirectionalCell, _format_sequence, _mask_sequence_variable_length

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask at every timestep (parity:
    contrib.rnn.VariationalDropoutCell, Gal & Ghahramani)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        assert not drop_states or not isinstance(base_cell,
                                                 BidirectionalCell), \
            "BidirectionalCell doesn't support variational state dropout."
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _initialize_mask(self, F, like, p):
        # one Bernoulli mask, reused across timesteps
        return F.Dropout(F.ones_like(like), p=p)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        if self.drop_states:
            if self.drop_states_mask is None:
                self.drop_states_mask = self._initialize_mask(
                    F, states[0], self.drop_states)
            states = [states[0] * self.drop_states_mask] + list(states[1:])
        if self.drop_inputs:
            if self.drop_inputs_mask is None:
                self.drop_inputs_mask = self._initialize_mask(
                    F, inputs, self.drop_inputs)
            inputs = inputs * self.drop_inputs_mask
        next_output, next_states = cell(inputs, states)
        if self.drop_outputs:
            if self.drop_outputs_mask is None:
                self.drop_outputs_mask = self._initialize_mask(
                    F, next_output, self.drop_outputs)
            next_output = next_output * self.drop_outputs_mask
        return next_output, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        return super(ModifierCell, self).unroll(
            length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)


class LSTMPCell(HybridRecurrentCell):
    """LSTM cell with projection (parity: contrib.rnn.LSTMPCell; the fused
    analogue is rnn.LSTM(projection_size=...))."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def infer_shape(self, inputs, states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        prev_h, prev_c = states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        sl = F.split_v2(gates, 4, axis=-1)
        in_gate = F.Activation(sl[0], act_type="sigmoid")
        forget_gate = F.Activation(sl[1], act_type="sigmoid")
        in_transform = F.Activation(sl[2], act_type="tanh")
        out_gate = F.Activation(sl[3], act_type="sigmoid")
        next_c = forget_gate * prev_c + in_gate * in_transform
        hidden = out_gate * F.Activation(next_c, act_type="tanh")
        next_r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]
