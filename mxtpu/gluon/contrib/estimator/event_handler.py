"""Estimator event handlers (parity: gluon/contrib/estimator/
event_handler.py — the 1.6+ training-loop hook system)."""

from __future__ import annotations

import logging
import os
import time
import warnings

import numpy as onp

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after max_epoch/max_batch (parity: StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch == self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch == self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset/update train metrics (parity: MetricHandler)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics or []
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs["pred"]
        label = kwargs["label"]
        loss = kwargs["loss"]
        for metric in self.metrics:
            from ....metric import Loss as LossMetric
            if isinstance(metric, LossMetric):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation periodically (parity: ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Log speed + metrics (parity: LoggingHandler)."""

    def __init__(self, log_interval="epoch", metrics=None, priority=-1000):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.log_interval_time = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        estimator.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        train_time = time.time() - self.train_start
        msg = "Train finished using total %ds with %d epochs. " % (
            train_time, self.current_epoch)
        for metric in self.metrics:
            name, value = metric.get()
            msg += "%s: %.4f, " % (name, value)
        estimator.logger.info(msg.rstrip(", "))

    def batch_begin(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int):
            self.batch_start = time.time()

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int):
            batch_time = time.time() - self.batch_start
            msg = "[Epoch %d][Batch %d]" % (self.current_epoch,
                                            self.batch_index)
            self.processed_samples += kwargs.get("batch_size", 0)
            msg += "[Samples %s] " % self.processed_samples
            self.log_interval_time += batch_time
            if self.batch_index % self.log_interval == 0:
                msg += "time/interval: %.3fs " % self.log_interval_time
                self.log_interval_time = 0
                for metric in self.metrics:
                    name, value = metric.get()
                    msg += "%s: %.4f, " % (name, value)
                estimator.logger.info(msg.rstrip(", "))
        self.batch_index += 1

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()

    def epoch_end(self, estimator, *args, **kwargs):
        epoch_time = time.time() - self.epoch_start
        msg = "[Epoch %d] finished in %.3fs: " % (self.current_epoch,
                                                  epoch_time)
        for monitor in self.metrics:
            name, value = monitor.get()
            msg += "%s: %.4f, " % (name, value)
        estimator.logger.info(msg.rstrip(", "))
        self.current_epoch += 1
        self.batch_index = 0


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save params (+trainer states) periodically, keep best (parity:
    CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.verbose = verbose
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.saved_checkpoints = []
        self.current_epoch = 0
        self.current_batch = 0
        if self.save_best and monitor is None:
            raise ValueError("save_best requires a monitor metric")
        if mode == "min":
            self.monitor_op = onp.less
        elif mode == "max":
            self.monitor_op = onp.greater
        else:
            self.monitor_op = onp.less if monitor is not None and \
                "loss" in (monitor.get()[0] or "") else onp.greater
        self.best = onp.inf if self.monitor_op == onp.less else -onp.inf

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        self.current_epoch = 0
        self.current_batch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save_checkpoint(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save_checkpoint(estimator)

    def _save_checkpoint(self, estimator):
        prefix = os.path.join(self.model_dir, self.model_prefix)
        fname = "%s-epoch%dbatch%d.params" % (prefix, self.current_epoch,
                                              self.current_batch)
        estimator.net.save_parameters(fname)
        self.saved_checkpoints.append(fname)
        while len(self.saved_checkpoints) > self.max_checkpoints:
            old = self.saved_checkpoints.pop(0)
            if os.path.exists(old):
                os.remove(old)
        if self.save_best:
            current = self.monitor.get()[1]
            if self.monitor_op(current, self.best):
                self.best = current
                estimator.net.save_parameters("%s-best.params" % prefix)
                if self.verbose:
                    estimator.logger.info(
                        "new best %s: %.5f; best model saved",
                        self.monitor.get()[0], current)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when a metric stops improving (parity: EarlyStoppingHandler)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        if mode == "min":
            self.monitor_op = onp.less
        elif mode == "max":
            self.monitor_op = onp.greater
        else:
            self.monitor_op = onp.less if "loss" in (
                monitor.get()[0] or "") else onp.greater
        if self.monitor_op == onp.greater:
            self.min_delta *= 1
        else:
            self.min_delta *= -1

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        self.best = onp.inf if self.monitor_op == onp.less else -onp.inf
        if self.baseline is not None:
            self.best = self.baseline

    def epoch_end(self, estimator, *args, **kwargs):
        monitor_name, monitor_value = self.monitor.get()
        if monitor_value is None or (isinstance(monitor_value, float)
                                     and onp.isnan(monitor_value)):
            warnings.warn("early stopping requires %s to be available" %
                          monitor_name)
        else:
            if self.monitor_op(monitor_value - self.min_delta, self.best):
                self.best = monitor_value
                self.wait = 0
            else:
                self.wait += 1
                if self.wait >= self.patience:
                    self.stopped_epoch = self.current_epoch
                    self.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            estimator.logger.info("[Epoch %d] early stopping",
                                  self.stopped_epoch)
