"""Estimator (parity: gluon/contrib/estimator/estimator.py — the 1.6+
high-level fit API over Gluon)."""

from __future__ import annotations

import copy
import logging
import warnings

from .... import autograd
from .... import metric as metric_mod
from ....metric import Accuracy, Loss as LossMetric
from ... import loss as gloss
from ...trainer import Trainer
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, ValidationHandler, LoggingHandler)

__all__ = ["Estimator"]


class Estimator:
    """Train a Gluon net with event handlers (parity: Estimator.fit)."""

    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        self.net = net
        self.logger = logging.getLogger("mxtpu.estimator")
        if not self.logger.handlers:
            self.logger.addHandler(logging.StreamHandler())
            self.logger.setLevel(logging.INFO)
        if isinstance(loss, gloss.Loss):
            self.loss = loss
        else:
            raise ValueError("loss must be a gluon.loss.Loss instance")
        if metrics is None:
            self.train_metrics = [Accuracy()]
        elif isinstance(metrics, (list, tuple)):
            self.train_metrics = list(metrics)
        else:
            self.train_metrics = [metrics]
        self.train_metrics.append(LossMetric(
            name="loss"))
        self.val_metrics = [copy.deepcopy(m) for m in self.train_metrics]
        self.context = context
        # initialize() on an already-initialized net warns and keeps the
        # existing values (Parameter.initialize semantics); real
        # initialization errors propagate
        self.net.initialize(init=initializer)
        self.trainer = trainer or Trainer(
            self.net.collect_params(), "adam", {"learning_rate": 1e-3})

    def evaluate(self, val_data, batch_axis=0):
        """(parity: Estimator.evaluate)"""
        for metric in self.val_metrics:
            metric.reset()
        for batch in val_data:
            data, label = batch[0], batch[1]
            pred = self.net(data)
            loss = self.loss(pred, label)
            for metric in self.val_metrics:
                if isinstance(metric, LossMetric):
                    metric.update(0, loss)
                else:
                    metric.update(label, pred)
        return [m.get() for m in self.val_metrics]

    def fit_batch(self, train_batch, batch_axis=0):
        data, label = train_batch[0], train_batch[1]
        with autograd.record():
            pred = self.net(data)
            loss = self.loss(pred, label)
        loss.backward()
        return data, label, pred, loss

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        """(parity: Estimator.fit)"""
        if epochs is None and batches is None:
            raise ValueError("please specify epochs or batches")
        event_handlers = self._prepare_default_handlers(
            val_data, event_handlers, epochs, batches)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize_handlers(event_handlers)
        stop_handlers = [h for h in event_handlers
                         if hasattr(h, "stop_training")]

        for handler in train_begin:
            handler.train_begin(self)
        stop = False
        while not stop:
            for handler in epoch_begin:
                handler.epoch_begin(self)
            for batch in train_data:
                for handler in batch_begin:
                    handler.batch_begin(self, batch=batch)
                data, label, pred, loss = self.fit_batch(batch, batch_axis)
                self.trainer.step(data.shape[batch_axis])
                for handler in batch_end:
                    handler.batch_end(self, batch=batch, pred=pred,
                                      label=label, loss=loss,
                                      batch_size=data.shape[batch_axis])
                if any(h.stop_training for h in stop_handlers):
                    stop = True
                    break
            if stop:
                break
            for handler in epoch_end:
                handler.epoch_end(self)
            if any(h.stop_training for h in stop_handlers):
                stop = True
        for handler in train_end:
            handler.train_end(self)

    def _prepare_default_handlers(self, val_data, event_handlers, epochs,
                                  batches):
        event_handlers = list(event_handlers or [])
        added = []
        if not any(isinstance(h, StoppingHandler) for h in event_handlers):
            event_handlers.append(StoppingHandler(epochs, batches))
            added.append("StoppingHandler")
        if not any(isinstance(h, MetricHandler) for h in event_handlers):
            event_handlers.append(MetricHandler(self.train_metrics))
            added.append("MetricHandler")
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in event_handlers):
            event_handlers.append(ValidationHandler(
                val_data, eval_fn=self.evaluate))
            added.append("ValidationHandler")
        if not any(isinstance(h, LoggingHandler) for h in event_handlers):
            event_handlers.append(LoggingHandler(
                metrics=self.train_metrics))
            added.append("LoggingHandler")
        if added:
            self.logger.info("default handlers added: %s", ", ".join(added))
        event_handlers.sort(key=lambda h: getattr(h, "priority", 0))
        return event_handlers

    @staticmethod
    def _categorize_handlers(event_handlers):
        return ([h for h in event_handlers if isinstance(h, TrainBegin)],
                [h for h in event_handlers if isinstance(h, EpochBegin)],
                [h for h in event_handlers if isinstance(h, BatchBegin)],
                [h for h in event_handlers if isinstance(h, BatchEnd)],
                [h for h in event_handlers if isinstance(h, EpochEnd)],
                [h for h in event_handlers if isinstance(h, TrainEnd)])
