"""Gluon: the imperative neural-network API (parity: python/mxnet/gluon/)."""

from . import parameter
from .parameter import Parameter, Constant, ParameterDict
from . import block
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from .loss import Loss
from . import trainer
from .trainer import Trainer
from . import utils


def __getattr__(name):
    # heavier subpackages (data pulls multiprocessing, rnn pulls scan paths,
    # model_zoo pulls every architecture) load lazily
    import importlib

    if name in ("data", "rnn", "model_zoo", "contrib"):
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxtpu.gluon' has no attribute {name!r}")
