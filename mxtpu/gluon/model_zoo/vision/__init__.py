"""Vision model zoo (parity: gluon/model_zoo/vision/__init__.py).

All architectures of the reference zoo: ResNet v1/v2 (18-152), VGG
(11-19, +BN), AlexNet, DenseNet (121-201), Inception-V3, MobileNet
v1/v2 (multiplier variants), SqueezeNet (1.0/1.1).
"""

from .resnet import *
from .vgg import *
from .alexnet import *
from .densenet import *
from .inception import *
from .mobilenet import *
from .squeezenet import *
from .resnet import get_resnet
from .vgg import get_vgg
from .mobilenet import get_mobilenet, get_mobilenet_v2


def get_model(name, **kwargs):
    """Look up a model by zoo name (parity: vision.get_model)."""
    import importlib

    models = {}
    # importlib, not `from . import X`: star-exports above shadow some
    # submodule names with factory functions (e.g. a `resnet` builder)
    for mod in (importlib.import_module("." + m, __package__)
                for m in ("resnet", "vgg", "alexnet", "densenet",
                          "inception", "mobilenet", "squeezenet")):
        for fname in mod.__all__:
            if fname.startswith(("get_", "Basic", "Bottleneck", "ResNet",
                                 "VGG", "AlexNet", "DenseNet", "Inception",
                                 "MobileNet", "SqueezeNet")):
                continue
            models[fname] = getattr(mod, fname)
    name = name.lower()
    if name not in models:
        raise ValueError(
            "Model %s is not supported. Available: %s" % (
                name, sorted(models.keys())))
    return models[name](**kwargs)
