"""Pretrained model store (parity: gluon/model_zoo/model_store.py).

Weights download requires network access; in air-gapped environments place
``<name>.params`` files under the root directory and they load directly.
"""

import os

__all__ = ["get_model_file", "purge"]

_DEFAULT_ROOT = os.path.join("~", ".mxtpu", "models")


def get_model_file(name, root=None):
    root = os.path.expanduser(root or _DEFAULT_ROOT)
    path = os.path.join(root, "%s.params" % name)
    if os.path.exists(path):
        return path
    raise FileNotFoundError(
        "Pretrained weights %s.params not found under %s. Download "
        "requires network access; place the file there manually in "
        "air-gapped environments." % (name, root))


def purge(root=None):
    root = os.path.expanduser(root or _DEFAULT_ROOT)
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
