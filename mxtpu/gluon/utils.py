"""Gluon utilities (parity: python/mxnet/gluon/utils.py): split_and_load,
clip_global_norm, check_sha1, download."""

from __future__ import annotations

import hashlib
import os
from typing import List

import jax.numpy as jnp
import numpy as onp

from ..base import MXTPUError
from ..context import Context
from ..ndarray import NDArray, array as nd_array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch_axis into num_slice slices (parity: split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. Use a batch size "
            f"that's multiple of {num_slice} or set even_split=False to "
            "allow uneven partitioning of data.")
    if num_slice == 1:
        return [data]
    step = size // num_slice
    if even_split:
        slices = [data.slice_axis(batch_axis, i * step, (i + 1) * step)
                  for i in range(num_slice)]
    else:
        slices = [data.slice_axis(batch_axis, i * step,
                                  (i + 1) * step if i < num_slice - 1
                                  else size)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data and load each slice onto one context
    (parity: split_and_load — the Gluon multi-device data-parallel entry)."""
    if not isinstance(data, NDArray):
        data = nd_array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the global 2-norm ≤ max_norm
    (parity: clip_global_norm; in-place like the reference)."""
    def _norm(a):
        return jnp.sum(jnp.square(a.data.astype(jnp.float32)))

    assert len(arrays) > 0
    total = jnp.sqrt(sum(_norm(a) for a in arrays))
    total_norm = float(total)
    if check_isfinite and not onp.isfinite(total_norm):
        import warnings
        warnings.warn(
            UserWarning("nan or inf is detected. Clipping results will be "
                        "undefined."), stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._rebind(arr.data * scale)
    return total_norm


def check_sha1(filename, sha1_hash):
    """Parity: check_sha1."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Parity: gluon.utils.download.  This build runs with zero egress, so
    the function only succeeds for file:// URLs or already-downloaded
    targets; otherwise it raises with a clear message."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    if url.startswith("file://"):
        import shutil
        shutil.copyfile(url[len("file://"):], fname)
        return fname
    raise MXTPUError(
        f"download({url!r}): network access is unavailable in this "
        "environment; place the file at {fname!r} manually")


def _brief_print_list(lst, limit=7):
    lst = list(lst)
    if len(lst) > limit:
        return _brief_print_list(lst[:limit // 2], limit) + ", ..., " + \
            _brief_print_list(lst[-limit // 2:], limit)
    return ", ".join(f"'{str(i)}'" for i in lst)


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s > 0 for s in shape)
