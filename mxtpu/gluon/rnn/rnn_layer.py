"""Fused RNN layers (parity: python/mxnet/gluon/rnn/rnn_layer.py — RNN,
LSTM, GRU backed by the fused ``rnn`` op; reference backend
src/operator/rnn.cc + cudnn_rnn-inl.h, here ops.nn.rnn over lax.scan)."""

from ... import ndarray as nd
from ...ndarray import NDArray
from ..block import HybridBlock
from ..parameter import tensor_types

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """Base for fused recurrent layers."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC', 'NTC']" % layout
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        nout = projection_size if projection_size else hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(
                    "{}{}_i2h_weight".format(j, i), (ng * nh, ni),
                    i2h_weight_initializer)
                self._register_param(
                    "{}{}_h2h_weight".format(j, i), (ng * nh, nout),
                    h2h_weight_initializer)
                self._register_param(
                    "{}{}_i2h_bias".format(j, i), (ng * nh,),
                    i2h_bias_initializer)
                self._register_param(
                    "{}{}_h2h_bias".format(j, i), (ng * nh,),
                    h2h_bias_initializer)
                if projection_size:
                    self._register_param(
                        "{}{}_h2r_weight".format(j, i), (projection_size, nh),
                        h2h_weight_initializer)
            ni = nout * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _collect_params_with_prefix(self, prefix=""):
        # parity quirk: fused-layer params serialize without the lN_ grouping
        return super()._collect_params_with_prefix(prefix)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (parity: _RNNLayer.begin_state)."""
        if func is None:
            func = nd.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info = dict(info)
                info.update(kwargs)
            else:
                info = dict(kwargs)
            info.pop("__layout__", None)
            if info.get("ctx") is None:
                info.pop("ctx", None)
            states.append(func(**info))
        return states

    def infer_shape(self, inputs, *args):
        assert inputs.ndim == 3, \
            "Input data should be rank-3 tensor of dim [T, N, C] or [N, T, C]"
        ch = inputs.shape[2]
        ni = ch
        nout = self._projection_size or self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                p = getattr(self, "{}{}_i2h_weight".format(j, i))
                if 0 in p.shape:
                    p.shape = (self._gates * self._hidden_size, ni)
            ni = nout * self._dir

    def hybrid_forward(self, F, inputs, states=None, sequence_length=None,
                       **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size,
                                      ctx=getattr(inputs, "context", None),
                                      dtype=inputs.dtype)
        if isinstance(states, NDArray):
            states = [states]
        # pack params into the cuDNN-layout vector the fused op expects
        flat = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                flat.append(params["{}{}_i2h_weight".format(j, i)].reshape(-1))
                flat.append(params["{}{}_h2h_weight".format(j, i)].reshape(-1))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                flat.append(params["{}{}_i2h_bias".format(j, i)].reshape(-1))
                flat.append(params["{}{}_h2h_bias".format(j, i)].reshape(-1))
        if self._projection_size:
            for i in range(self._num_layers):
                for j in ["l", "r"][:self._dir]:
                    flat.append(
                        params["{}{}_h2r_weight".format(j, i)].reshape(-1))
        packed = F.concat(*flat, dim=0) if len(flat) > 1 else flat[0]
        rnn_args = [packed] + list(states)
        out = F.RNN(inputs, *rnn_args, state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True, sequence_length=sequence_length,
                    use_sequence_length=sequence_length is not None,
                    projection_size=self._projection_size)
        outputs, states = out[0], list(out[1:])
        if self._layout == "NTC":
            outputs = nd.swapaxes(outputs, 0, 1)
        if skip_states:
            return outputs
        return outputs, states

    def forward(self, inputs, states=None, sequence_length=None):
        return super().forward(inputs, states, sequence_length)


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (relu or tanh) (parity: rnn.RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (parity: rnn.LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "lstm", projection_size, **kwargs)

    def state_info(self, batch_size=0):
        h_size = self._projection_size or self._hidden_size
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           h_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (parity: rnn.GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
