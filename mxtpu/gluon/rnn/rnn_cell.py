"""Unfused RNN cells (parity: python/mxnet/gluon/rnn/rnn_cell.py).

Cells run stepwise imperatively; `unroll` loops in Python, which under
hybridize traces into one XLA program (the reference unrolled into a
symbolic graph the same way — src call path gluon/rnn/rnn_cell.py unroll).
For long sequences prefer the fused rnn_layer.LSTM/GRU (lax.scan).
"""

from ... import ndarray as nd
from ...ndarray import NDArray
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, batch_size=0, **kwargs):
    return sum([c.begin_state(batch_size=batch_size, **kwargs)
                for c in cells], [])


def _as_list(x):
    """split_v2 returns a bare NDArray for a single section (parity with
    the reference's split) — sequence helpers always want a list."""
    return [x] if isinstance(x, NDArray) else list(x)


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    batch_size = 0
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            assert length is None or length == inputs.shape[in_axis]
            inputs = _as_list(nd.split_v2(inputs, inputs.shape[in_axis],
                                          axis=in_axis, squeeze_axis=True))
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = nd.stack(*inputs, axis=axis)
            in_axis = axis
    if isinstance(inputs, NDArray) and axis != in_axis:
        inputs = nd.swapaxes(inputs, axis, in_axis)
    return inputs, axis, batch_size


def _mask_sequence_variable_length(data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, NDArray):
        data = nd.stack(*data, axis=time_axis)
    outputs = nd.SequenceMask(data, sequence_length=valid_length,
                              use_sequence_length=True, axis=time_axis)
    if not merge:
        outputs = _as_list(nd.split_v2(outputs, outputs.shape[time_axis],
                                       axis=time_axis, squeeze_axis=True))
    return outputs


class RecurrentCell(Block):
    """Abstract recurrent step (parity: rnn.RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info = dict(info)
                info.update(kwargs)
            else:
                info = dict(kwargs)
            info.pop("__layout__", None)
            states.append(func(**info))
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell `length` steps (parity: RecurrentCell.unroll)."""
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [nd.SequenceLast(nd.stack(*ele_list, axis=0),
                                      sequence_length=valid_length,
                                      use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(
                outputs, length, valid_length, axis, True)
        if merge_outputs is not False:
            outputs = outputs if isinstance(outputs, NDArray) else \
                nd.stack(*outputs, axis=axis)
        elif isinstance(outputs, NDArray):
            outputs = _as_list(nd.split_v2(outputs, outputs.shape[axis],
                                           axis=axis, squeeze_axis=True))
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        raise NotImplementedError


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell with hybridizable step."""

    def forward(self, inputs, states):
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(W_i x + b_i + W_h h + b_h)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, inputs, states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self._hidden_size, inputs.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (gates i,f,g,o — cuDNN order, same as the fused op)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None,
                 activation="tanh", recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)
        self._activation = activation
        self._recurrent_activation = recurrent_activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, inputs, states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split_v2(gates, 4, axis=-1)
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2],
                                            self._activation)
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (gates r,z,n — cuDNN order)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, inputs, states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (3 * self._hidden_size, inputs.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split_v2(i2h, 3, axis=-1)
        h2h_r, h2h_z, h2h = F.split_v2(h2h, 3, axis=-1)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (parity: rnn.SequentialRNNCell)."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), batch_size,
                                  **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        assert all(not isinstance(cell, BidirectionalCell)
                   for cell in self._children.values())
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        num_cells = len(self._children)
        _, _, batch_size = _format_sequence(length, inputs, layout, None)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args):
        raise NotImplementedError


class HybridSequentialRNNCell(HybridRecurrentCell):
    """Hybridizable stack of cells."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), batch_size,
                                  **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    unroll = SequentialRNNCell.unroll
    __getitem__ = SequentialRNNCell.__getitem__
    __len__ = SequentialRNNCell.__len__


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (dropout/zoneout/residual)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs)
        self.base_cell._modified = True
        return begin

    def _alias(self):
        return "modifier"


class DropoutCell(HybridRecurrentCell):
    """Apply dropout on input (parity: rnn.DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, (int, float))
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (parity: rnn.ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = (F.where(mask(self.zoneout_outputs, next_output),
                          next_output, prev_output)
                  if self.zoneout_outputs > 0.0 else next_output)
        states = ([F.where(mask(self.zoneout_states, new_s), new_s, old_s)
                   for new_s, old_s in zip(next_states, states)]
                  if self.zoneout_states > 0.0 else next_states)
        self._prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds residual connection around the base cell."""

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, NDArray) if merge_outputs is \
            None else merge_outputs
        inputs, axis, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [o + i for o, i in zip(outputs, inputs)]
        if valid_length is not None:
            outputs = _mask_sequence_variable_length(
                outputs, length, valid_length, axis, merge_outputs)
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Run two cells over the sequence in both directions (parity:
    rnn.BidirectionalCell). Stepwise call is invalid; only unroll."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), batch_size,
                                  **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        reversed_inputs = list(reversed(inputs))
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=merge_outputs,
            valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is not None:
            # reverse the valid part back into place
            r_outputs = nd.SequenceReverse(
                nd.stack(*r_outputs, axis=0), sequence_length=valid_length,
                use_sequence_length=True, axis=0)
            r_outputs = _as_list(nd.split_v2(r_outputs, r_outputs.shape[0],
                                             axis=0, squeeze_axis=True))
        else:
            r_outputs = list(reversed(r_outputs))
        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, NDArray)
        if merge_outputs and not isinstance(l_outputs, NDArray):
            l_outputs = nd.stack(*l_outputs, axis=axis)
        if merge_outputs:
            r_merged = nd.stack(*r_outputs, axis=axis) \
                if not isinstance(r_outputs, NDArray) else r_outputs
            outputs = nd.concat(l_outputs, r_merged, dim=2)
        else:
            outputs = [nd.concat(l_o, r_o, dim=1)
                       for l_o, r_o in zip(l_outputs, r_outputs)]
        if valid_length is not None:
            outputs = _mask_sequence_variable_length(
                outputs, length, valid_length, axis, merge_outputs)
        states = l_states + r_states
        return outputs, states
