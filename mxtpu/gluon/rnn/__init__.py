"""Gluon RNN API (parity: python/mxnet/gluon/rnn/)."""

from .rnn_cell import (RecurrentCell, HybridRecurrentCell, RNNCell, LSTMCell,
                       GRUCell, SequentialRNNCell, HybridSequentialRNNCell,
                       DropoutCell, ZoneoutCell, ResidualCell,
                       BidirectionalCell)
from .rnn_layer import RNN, LSTM, GRU
