"""Test utilities (parity: python/mxnet/test_utils.py — assert_almost_equal,
check_numeric_gradient, check_consistency, rand_ndarray, default_context).

check_consistency compares across available jax backends (CPU vs TPU) the
way the reference compared CPU vs GPU vs cuDNN (SURVEY §4 fixture 2);
check_numeric_gradient validates the tape against finite differences
(fixture 3)."""

from __future__ import annotations

import os

import numpy as onp

from . import autograd
from . import context as ctx_mod
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_nd",
           "random_seed", "check_numeric_gradient", "check_consistency",
           "check_symbolic_forward", "check_symbolic_backward",
           "simple_forward", "list_gpus"]

_default_ctx = None


def default_context():
    """Env-driven default test context (parity: env MXNET_TEST_DEVICE)."""
    global _default_ctx
    if _default_ctx is not None:
        return _default_ctx
    dev = os.environ.get("MXTPU_TEST_DEVICE", "")
    if dev.startswith("tpu"):
        return ctx_mod.tpu(0)
    if dev.startswith("cpu") or not ctx_mod.num_tpus():
        return ctx_mod.cpu()
    return ctx_mod.tpu(0)


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def _as_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return onp.asarray(a)


def default_rtols(dtype):
    return {"float16": 1e-2, "bfloat16": 2e-2, "float32": 1e-4,
            "float64": 1e-7}.get(str(dtype), 1e-4)


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=None, atol=None):
    a, b = _as_np(a), _as_np(b)
    rtol = rtol if rtol is not None else default_rtols(a.dtype)
    atol = atol if atol is not None else 1e-6
    return onp.allclose(a, b, rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    an, bn = _as_np(a), _as_np(b)
    if an.dtype == onp.dtype("bfloat16") if hasattr(onp, "bfloat16") else \
            False:
        an = an.astype("float32")
    rtol = rtol if rtol is not None else default_rtols(an.dtype)
    atol = atol if atol is not None else 1e-6
    onp.testing.assert_allclose(
        an.astype("float64") if an.dtype.kind == "V" else an,
        bn.astype("float64") if bn.dtype.kind == "V" else bn,
        rtol=rtol, atol=atol, equal_nan=equal_nan,
        err_msg="%s vs %s" % names)


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 ctx=None):
    if stype != "default":
        import warnings
        warnings.warn("sparse stype descoped; returning dense")
    return nd.array(onp.random.uniform(-1, 1, shape).astype(dtype), ctx=ctx)


class random_seed:
    """Context manager seeding mx+numpy deterministically
    (parity: tests/python/unittest/common.py with_seed)."""

    def __init__(self, seed=None):
        self.seed = seed

    def __enter__(self):
        from . import random as _rnd
        self.used = self.seed if self.seed is not None else \
            onp.random.randint(0, 2 ** 31)
        _rnd.seed(self.used)
        onp.random.seed(self.used)
        return self.used

    def __exit__(self, etype, *a):
        if etype is not None:
            print("random_seed: failing seed was %d" % self.used)


def simple_forward(fn, *inputs):
    out = fn(*[nd.array(i) for i in inputs])
    if isinstance(out, (list, tuple)):
        return [o.asnumpy() for o in out]
    return out.asnumpy()


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4):
    """Finite-difference check of tape gradients (parity:
    test_utils.check_numeric_gradient; fn: list[NDArray] → scalar NDArray).
    """
    arrays = [nd.array(_as_np(i).astype("float64").astype("float32"))
              for i in inputs]
    for a in arrays:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrays)
        assert out.size == 1, "fn must reduce to a scalar"
    out.backward()
    for idx, a in enumerate(arrays):
        analytic = a.grad.asnumpy()
        base = a.asnumpy().copy()
        numeric = onp.zeros_like(base)
        flat = base.reshape(-1)
        num_flat = numeric.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            a2 = [nd.array(base.reshape(a.shape)) if j == idx else arrays[j]
                  for j in range(len(arrays))]
            fp = float(fn(*a2).asnumpy())
            flat[i] = orig - eps
            a2 = [nd.array(base.reshape(a.shape)) if j == idx else arrays[j]
                  for j in range(len(arrays))]
            fm = float(fn(*a2).asnumpy())
            flat[i] = orig
            num_flat[i] = (fp - fm) / (2 * eps)
        onp.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                                    err_msg="input %d gradient" % idx)


def check_consistency(fn, inputs, ctx_list=None, rtol=None, atol=None):
    """Run fn on each context and compare outputs (parity:
    test_utils.check_consistency across CPU/GPU/cuDNN backends)."""
    if ctx_list is None:
        ctx_list = [ctx_mod.cpu()]
        if ctx_mod.num_tpus():
            ctx_list.append(ctx_mod.tpu(0))
    outs = []
    for ctx in ctx_list:
        arrs = [nd.array(_as_np(i), ctx=ctx) for i in inputs]
        out = fn(*arrs)
        outs.append(_as_np(out))
    ref = outs[0]
    for o, ctx in zip(outs[1:], ctx_list[1:]):
        assert_almost_equal(ref, o, rtol=rtol, atol=atol,
                            names=("ctx0", str(ctx)))
    return outs


def list_gpus():
    return list(range(ctx_mod.num_tpus()))


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           aux_states=None, ctx=None):
    """Bind a symbol, run forward, compare each output against expected
    (parity: test_utils.check_symbolic_forward).  location: dict
    name→array or list in list_arguments() order."""
    ctx = ctx or default_context()
    args = _location_dict(sym.list_arguments(), location)
    auxs = _location_dict(sym.list_auxiliary_states(), aux_states or {})
    ex = sym.bind(ctx, args, aux_states=auxs)
    outs = ex.forward()
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    assert len(outs) == len(expected), \
        "output arity %d != expected %d" % (len(outs), len(expected))
    for i, (o, e) in enumerate(zip(outs, expected)):
        assert_almost_equal(_as_np(o), _as_np(e), rtol, atol,
                            names=("output[%d]" % i, "expected[%d]" % i))
    return outs


def check_symbolic_backward(sym, location, out_grads, expected_grads,
                            rtol=1e-4, atol=1e-5, aux_states=None,
                            grad_req="write", ctx=None):
    """Bind with gradient buffers, run forward+backward, compare each
    argument gradient against expected (parity:
    test_utils.check_symbolic_backward).  expected_grads: dict
    name→array (only named args are checked)."""
    ctx = ctx or default_context()
    args = _location_dict(sym.list_arguments(), location)
    auxs = _location_dict(sym.list_auxiliary_states(), aux_states or {})
    grads = {k: nd.zeros_like(v) for k, v in args.items()}
    ex = sym.bind(ctx, args, args_grad=grads, grad_req=grad_req,
                  aux_states=auxs)
    ex.forward(is_train=True)
    if out_grads is not None and not isinstance(out_grads, (list, tuple)):
        out_grads = [out_grads]
    if out_grads is not None:
        out_grads = [g if isinstance(g, nd.NDArray) else nd.array(g)
                     for g in out_grads]
    ex.backward(out_grads)
    for name, exp in expected_grads.items():
        got = ex.grad_dict.get(name)
        assert got is not None, "no gradient recorded for %r" % name
        assert_almost_equal(_as_np(got), _as_np(exp), rtol, atol,
                            names=("grad[%s]" % name, "expected"))
    return ex.grad_dict


def _location_dict(names, location):
    if isinstance(location, dict):
        return {k: (v if isinstance(v, nd.NDArray) else nd.array(v))
                for k, v in location.items() if k in set(names)}
    return {n: (v if isinstance(v, nd.NDArray) else nd.array(v))
            for n, v in zip(names, location)}
