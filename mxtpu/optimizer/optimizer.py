"""Optimizers (parity: python/mxnet/optimizer/optimizer.py + the fused update
ops in src/operator/optimizer_op.cc: sgd_update, sgd_mom_update, adam_update,
lamb_update_phase1/2, signsgd_update, ...).

TPU design: the reference fuses each update rule into a single CUDA kernel;
here each rule is a pure function ``_step(weight, grad, state, lr, wd) ->
(new_weight, new_state)`` jitted once per (shape, dtype) — XLA fuses the whole
rule into one kernel and the scalar hyperparameters (lr, wd) are passed as
device scalars so changing them never recompiles.  The imperative ``update``
API (index-keyed, mutating) matches the reference exactly so Trainer/Module
and the kvstore updater work unchanged.
"""

from __future__ import annotations

import functools
import math
import pickle
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXTPUError
from ..ndarray import NDArray
from .lr_scheduler import LRScheduler

__all__ = [
    "Optimizer", "register", "create", "get_updater", "Updater",
    "SGD", "NAG", "Signum", "SGLD", "Adam", "AdamW", "AdaGrad", "AdaDelta",
    "RMSProp", "Ftrl", "LAMB", "LARS", "Test",
]


def _clip(x, bound):
    return jnp.clip(x, -bound, bound) if bound is not None and bound > 0 else x


class Optimizer:
    """Base optimizer (parity: mx.optimizer.Optimizer).

    Subclasses implement ``create_state`` and ``_step``; the base handles
    lr/wd multipliers, gradient rescale/clip, update counting and schedulers.
    """

    opt_registry: Dict[str, type] = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        if not isinstance(param_idx2name, dict):
            raise MXTPUError("param_idx2name should be a dict of param indexes to names.")
        self.idx2name = param_idx2name.copy()
        self.sym_info = ()
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    # -- registry --------------------------------------------------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError(f"Cannot find optimizer {name}")

    # -- state -----------------------------------------------------------
    def create_state(self, index, weight):
        """Return the state pytree of jax arrays for one parameter."""
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.data.dtype == jnp.bfloat16:
            w32 = weight.data.astype(jnp.float32)
            return (w32, self.create_state(index, NDArray(w32)))
        return self.create_state(index, weight)

    # -- rule ------------------------------------------------------------
    def _step(self, weight, grad, state, lr, wd):
        """Pure update rule over jax arrays; override in subclasses."""
        raise NotImplementedError

    def _step_t(self, weight, grad, state, lr, wd, t):
        """Pure update rule with the update count ``t`` as a traced device
        scalar.  This is the SPMD entry point (SPMDTrainer jits it inside
        the train step): optimizers whose rule depends on the step count
        (Adam bias correction, LAMB) override it so the correction happens
        on device and no host-side isinstance special-casing is needed.
        Default delegates to ``_step`` (t-independent rules)."""
        return self._step(weight, grad, state, lr, wd)

    @functools.lru_cache(maxsize=None)
    def _jit_step(self):
        # donate weight and state buffers: the old values die with the update,
        # matching the reference's in-place fused optimizer ops.
        return jax.jit(self._step, donate_argnums=(0, 2))

    def _ledger_observe(self, weight, grad):
        """Report this per-parameter compiled update into the process
        compile ledger (docs/analysis.md).  jax.jit keeps the executable
        cache internally (one retrace per distinct shape/dtype), so the
        ledger tracks the seen-signature set itself — this is how the
        gluon Trainer's compiled steps become visible to compile_check.
        Gated before the signature build: this runs per parameter per
        step."""
        from ..analysis.compile_ledger import (Signature, ledger_enabled,
                                               observe)
        if not ledger_enabled():
            return
        observe("optimizer.%s" % type(self).__name__.lower(), Signature(
            shapes=(tuple(weight.shape), tuple(grad.shape)),
            dtypes=(str(weight.dtype), str(grad.dtype)),
            weak=(), static=()))

    def update(self, index, weight, grad, state):
        """Imperative entry (parity: Optimizer.update).  Mutates weight/state."""
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._ledger_observe(weight, grad)
        new_w, new_state = self._jit_step()(
            weight.data, grad.data, state,
            jnp.float32(lr), jnp.float32(wd))
        weight._rebind(new_w)
        return new_state

    def _update_row_sparse(self, index, weight, grad, state):
        """Row-sparse (lazy) update: run the optimizer's OWN update() on
        views of the touched rows only, then scatter the results back
        (parity: sparse sgd_update / lazy adam semantics — state rows of
        untouched ids do not advance).  Row-local rules get this fast
        path; cross-row rules (LAMB/LARS global norms, ...) densify the
        gradient instead (exact, documented fallback)."""
        from ..ndarray.ndarray import NDArray
        if not self._row_sparse_safe():
            # cross-row rules (LAMB/LARS global norms, ...): exact dense
            # fallback through the normal (multi-precision-aware) entry
            return self.update_multi_precision(index, weight,
                                               grad.todense(), state)
        ids = grad.indices.data
        wnd = weight.data
        rows = NDArray(jnp.take(wnd, ids, axis=0))
        is_rowwise = lambda s: getattr(s, "ndim", -1) == wnd.ndim and \
            s.shape[0] == wnd.shape[0]  # noqa: E731
        row_state = jax.tree_util.tree_map(
            lambda s: jnp.take(s, ids, axis=0) if is_rowwise(s) else s,
            state)
        new_row_state = self.update(index, rows, grad.data, row_state)
        weight._rebind(wnd.at[ids].set(rows.data.astype(wnd.dtype)))
        return jax.tree_util.tree_map(
            lambda s, nrs: s.at[ids].set(nrs) if is_rowwise(s) else nrs,
            state, new_row_state)

    def _row_sparse_safe(self):
        """Whether the update rule is row-local (no cross-row coupling),
        making the lazy row update equal to the reference's sparse path."""
        return type(self).__name__ in ("SGD", "NAG", "Adam", "AdamW",
                                       "AdaGrad", "RMSProp")

    def update_multi_precision(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            if self.multi_precision and weight.data.dtype == jnp.bfloat16:
                # multi-precision state is (w32, inner): the lazy row path
                # would thread the tuple into the rule — densify instead
                # (exact, just not lazy; rare combo)
                return self.update_multi_precision(index, weight,
                                                   grad.todense(), state)
            return self._update_row_sparse(index, weight, grad, state)
        if self.multi_precision and weight.data.dtype == jnp.bfloat16:
            w32, inner = state
            g32 = grad.data.astype(jnp.float32)
            self._update_count(index)
            lr = self._get_lr(index)
            wd = self._get_wd(index)
            new_w32, new_inner = self._jit_step()(
                w32, g32, inner, jnp.float32(lr), jnp.float32(wd))
            weight._rebind(new_w32.astype(jnp.bfloat16))
            return (new_w32, new_inner)
        return self.update(index, weight, grad, state)

    # -- hyper-parameter plumbing (parity with reference) ----------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXTPUError("LRScheduler of the optimizer has already been "
                             "defined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx],
                                  self.num_update)

    def _get_lrs(self, indices):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        lrs = [lr] * len(indices)
        for i, index in enumerate(indices):
            if index in self.param_dict:
                lrs[i] *= self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                lrs[i] *= self.lr_mult[index]
            elif index in self.idx2name:
                lrs[i] *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = [self.wd] * len(indices)
        for i, index in enumerate(indices):
            if index in self.param_dict:
                wds[i] *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wds[i] *= self.wd_mult[index]
            elif index in self.idx2name:
                wds[i] *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def __getstate__(self):
        ret = self.__dict__.copy()
        return ret


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class SGD(Optimizer):
    """SGD with momentum and weight decay (parity: sgd_update /
    sgd_mom_update in src/operator/optimizer_op.cc):

        grad = rescale_grad * clip(grad) + wd * weight
        mom  = momentum * mom - lr * grad
        weight += mom
    """

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update  # sparse-only knob; dense ignores

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return jnp.zeros(weight.shape, weight.data.dtype)

    def _step(self, weight, grad, state, lr, wd):
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        g = g + wd * weight
        if self.momentum == 0.0:
            return weight - lr * g, None
        mom = self.momentum * state - lr * g
        return weight + mom, mom


@register
class NAG(SGD):
    """Nesterov accelerated SGD (parity: nag_mom_update)."""

    def _step(self, weight, grad, state, lr, wd):
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        g = g + wd * weight
        if self.momentum == 0.0:
            return weight - lr * g, None
        mom = self.momentum * state - lr * g
        return weight + self.momentum * mom - lr * g, mom


@register
class Signum(Optimizer):
    """signSGD / Signum (parity: signsgd_update / signum_update)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return jnp.zeros(weight.shape, weight.data.dtype)

    def _step(self, weight, grad, state, lr, wd):
        if self.momentum == 0.0:
            g = _clip(grad * self.rescale_grad, self.clip_gradient)
            return weight * (1 - lr * wd) - lr * jnp.sign(g), None
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        mom = self.momentum * state - (1 - self.momentum) * (g + wd * weight)
        return weight * (1 - lr * self.wd_lh) + lr * jnp.sign(mom), mom


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (parity: SGLD)."""

    def create_state(self, index, weight):
        from .. import random as _rnd
        return None

    def update(self, index, weight, grad, state):
        from .. import random as _rnd
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        key = _rnd.next_key()
        g = _clip(grad.data * self.rescale_grad, self.clip_gradient)
        g = g + wd * weight.data
        noise = jax.random.normal(key, weight.shape, jnp.float32) * math.sqrt(lr)
        weight._rebind(weight.data - lr / 2 * g
                       + noise.astype(weight.data.dtype))
        return state


@register
class Adam(Optimizer):
    """Adam (parity: adam_update; bias correction folded into lr like the
    reference's coef computation in the Python layer)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight.data.dtype),
                jnp.zeros(weight.shape, weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr = self._get_lr(index) * math.sqrt(coef2) / coef1
        wd = self._get_wd(index)
        self._ledger_observe(weight, grad)
        new_w, new_state = self._jit_step()(
            weight.data, grad.data, state, jnp.float32(lr), jnp.float32(wd))
        weight._rebind(new_w)
        return new_state

    def _step(self, weight, grad, state, lr, wd):
        mean, var = state
        g = _clip(grad * self.rescale_grad, self.clip_gradient) + wd * weight
        mean = self.beta1 * mean + (1. - self.beta1) * g
        var = self.beta2 * var + (1. - self.beta2) * g * g
        w = weight - lr * mean / (jnp.sqrt(var) + self.epsilon)
        return w, (mean, var)

    def _step_t(self, weight, grad, state, lr, wd, t):
        # bias correction folded into lr on device (same coef math as
        # update(), but t is traced so one compiled step serves all steps)
        t = jnp.asarray(t, jnp.float32)
        lr = lr * jnp.sqrt(1. - self.beta2 ** t) / (1. - self.beta1 ** t)
        return self._step(weight, grad, state, lr, wd)


@register
class AdamW(Adam):
    """Adam with decoupled weight decay (parity: contrib adamw_update)."""

    def _step(self, weight, grad, state, lr, wd):
        mean, var = state
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        mean = self.beta1 * mean + (1. - self.beta1) * g
        var = self.beta2 * var + (1. - self.beta2) * g * g
        w = weight - lr * (mean / (jnp.sqrt(var) + self.epsilon) + wd * weight)
        return w, (mean, var)


@register
class AdaGrad(Optimizer):
    """AdaGrad (parity: AdaGrad in optimizer.py; history += g^2)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return jnp.zeros(weight.shape, weight.data.dtype)

    def _step(self, weight, grad, state, lr, wd):
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        g = g + wd * weight
        history = state + g * g
        w = weight - lr * g / (jnp.sqrt(history) + self.float_stable_eps)
        return w, history


@register
class AdaDelta(Optimizer):
    """AdaDelta (parity: AdaDelta)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight.data.dtype),
                jnp.zeros(weight.shape, weight.data.dtype))

    def _step(self, weight, grad, state, lr, wd):
        acc_g, acc_delta = state
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        g = g + wd * weight
        acc_g = self.rho * acc_g + (1. - self.rho) * g * g
        delta = (jnp.sqrt(acc_delta + self.epsilon)
                 / jnp.sqrt(acc_g + self.epsilon)) * g
        acc_delta = self.rho * acc_delta + (1. - self.rho) * delta * delta
        return weight - delta, (acc_g, acc_delta)


@register
class RMSProp(Optimizer):
    """RMSProp (parity: rmsprop_update / rmspropalex_update; centered=True
    uses Graves' variant like the reference)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = jnp.zeros(weight.shape, weight.data.dtype)
        if self.centered:
            return (z, z, z)  # n, g, delta
        return z  # n

    def _step(self, weight, grad, state, lr, wd):
        grad = _clip(grad * self.rescale_grad, self.clip_gradient)
        grad = grad + wd * weight
        if not self.centered:
            n = state
            n = (1. - self.gamma1) * grad * grad + self.gamma1 * n
            w = weight - lr * grad / jnp.sqrt(n + self.epsilon)
            w = _clip(w, self.clip_weights)
            return w, n
        n, g, delta = state
        n = (1. - self.gamma1) * grad * grad + self.gamma1 * n
        g = (1. - self.gamma1) * grad + self.gamma1 * g
        delta = self.gamma2 * delta - lr * grad / jnp.sqrt(
            n - g * g + self.epsilon)
        w = _clip(weight + delta, self.clip_weights)
        return w, (n, g, delta)


@register
class Ftrl(Optimizer):
    """FTRL-proximal (parity: ftrl_update)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight.data.dtype),  # z
                jnp.zeros(weight.shape, weight.data.dtype))  # n

    def _step(self, weight, grad, state, lr, wd):
        z, n = state
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
        z = z + g - sigma * weight
        n = n + g * g
        w = ((jnp.sign(z) * self.lamda1 - z)
             / ((self.beta + jnp.sqrt(n)) / lr + wd)
             * (jnp.abs(z) > self.lamda1))
        return w, (z, n)


@register
class LAMB(Optimizer):
    """LAMB layer-wise adaptive optimizer for large-batch BERT training
    (parity: lamb_update_phase1/phase2, 1.6+)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (jnp.zeros(weight.shape, weight.data.dtype),
                jnp.zeros(weight.shape, weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._ledger_observe(weight, grad)
        new_w, new_state = self._jit_t_step()(
            weight.data, grad.data, state, jnp.float32(lr), jnp.float32(wd),
            jnp.float32(t))
        weight._rebind(new_w)
        return new_state

    @functools.lru_cache(maxsize=None)
    def _jit_t_step(self):
        return jax.jit(self._t_step, donate_argnums=(0, 2))

    def _step_t(self, weight, grad, state, lr, wd, t):
        return self._t_step(weight, grad, state, lr, wd, t)

    def _t_step(self, weight, grad, state, lr, wd, t):
        mean, var = state
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        mean = self.beta1 * mean + (1. - self.beta1) * g
        var = self.beta2 * var + (1. - self.beta2) * g * g
        if self.bias_correction:
            mean_hat = mean / (1. - self.beta1 ** t)
            var_hat = var / (1. - self.beta2 ** t)
        else:
            mean_hat, var_hat = mean, var
        update = mean_hat / (jnp.sqrt(var_hat) + self.epsilon) + wd * weight
        w_norm = jnp.linalg.norm(weight)
        u_norm = jnp.linalg.norm(update)
        if self.lower_bound is not None:
            w_norm = jnp.maximum(w_norm, self.lower_bound)
        if self.upper_bound is not None:
            w_norm = jnp.minimum(w_norm, self.upper_bound)
        ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return weight - lr * ratio * update, (mean, var)


@register
class LARS(Optimizer):
    """LARS layer-wise adaptive rate scaling (parity: LARS, 1.6+)."""

    def __init__(self, momentum=0.9, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return jnp.zeros(weight.shape, weight.data.dtype)

    def _step(self, weight, grad, state, lr, wd):
        g = _clip(grad * self.rescale_grad, self.clip_gradient)
        w_norm = jnp.linalg.norm(weight)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon), 1.0)
        g = g + wd * weight
        mom = self.momentum * state - lr * trust * g
        return weight + mom, mom


@register
class Test(Optimizer):
    """Trivial optimizer for tests (parity: mx.optimizer.Test)."""

    def create_state(self, index, weight):
        return jnp.zeros(weight.shape, weight.data.dtype)

    def _step(self, weight, grad, state, lr, wd):
        return weight + grad * self.rescale_grad, state


class Updater:
    """Applies an optimizer keyed by integer index, holding per-index state
    (parity: mx.optimizer.Updater / get_updater; this is the object the
    KVStore runs server-side when update_on_kvstore=True)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = False

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            indices, grads, weights = [index], [grad], [weight]
        else:
            indices, grads, weights = index, grad, weight
        for i, g, w in zip(indices, grads, weights):
            if i not in self.states:
                self.states[i] = self.optimizer.create_state_multi_precision(
                    i, w)
                self.states_synced[i] = True
            self.states[i] = self.optimizer.update_multi_precision(
                i, w, g, self.states[i])

    def get_states(self, dump_optimizer=False):
        states = {k: jax.tree_util.tree_map(onp.asarray, v)
                  for k, v in self.states.items()}
        if dump_optimizer:
            # reference parity: param_dict is runtime wiring (live
            # Parameters holding device buffers), not optimizer state —
            # strip it for the pickle (depending on backend state the
            # buffers can drag unpicklable Device refs into the dump)
            # and restore after; the loading Trainer rebuilds it from
            # its own params
            pd = self.optimizer.param_dict
            self.optimizer.param_dict = {}
            try:
                return pickle.dumps((states, self.optimizer))
            finally:
                self.optimizer.param_dict = pd
        return pickle.dumps(states)

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2 and not isinstance(
                states[0], onp.ndarray):
            try:
                prev = self.optimizer
                states, self.optimizer = states
                # the dump strips param_dict (see get_states); inherit
                # the live wiring so per-param lr_mult/wd_mult keep
                # applying for direct kvstore save/load round-trips
                # (gluon Trainer.load_states rebuilds it afterwards
                # regardless)
                if not getattr(self.optimizer, "param_dict", None) \
                        and prev is not None:
                    self.optimizer.param_dict = prev.param_dict
            except Exception:
                pass
        self.states = {
            k: jax.tree_util.tree_map(jnp.asarray, v)
            for k, v in states.items()}
        self.states_synced = dict.fromkeys(self.states.keys(), False)


def get_updater(optimizer):
    return Updater(optimizer)
