"""Optimizer API (parity: python/mxnet/optimizer/)."""

from . import lr_scheduler
from .lr_scheduler import *
from .optimizer import *
from .optimizer import Optimizer, register, create, get_updater, Updater

opt = create  # parity alias: mx.optimizer.opt
