"""One metrics registry across serving and training.

Telemetry used to be scattered over five uncoordinated surfaces —
engine ``stats``, ``resilience.counters()``, the CompileLedger,
gateway/router/supervisor stats, guardian counters — that
``tools/diagnose.py`` and ``bench.py`` each hand-stitched.  The
:class:`MetricsRegistry` is the one collection point: named SOURCES
(callables returning nested dicts) are pulled LAZILY at
:meth:`~MetricsRegistry.snapshot` time and flattened into a single
``{"source.key.subkey": number}`` dict, with :meth:`~MetricsRegistry.
delta` for before/after reads and Prometheus-text + JSON exposition.

Built-in sources of the process registry (:func:`get_registry`):

==================  ====================================================
source              pulls
==================  ====================================================
``resilience``      :func:`mxtpu.resilience.counters` (process-wide
                    fault/retry/quarantine/guardian counters)
``compile_ledger``  per-site compiled-program counts from the
                    :class:`~mxtpu.analysis.compile_ledger.CompileLedger`
                    (``compile_ledger.<site>.programs`` — the key shape
                    the O001 obs_check pass cross-checks)
``engine_bulk``     :func:`mxtpu.engine.bulk_stats` (segment cache)
``profiler``        :func:`mxtpu.profiler.counter_values` (the parity
                    Counter API's values — ``profiler.dumps`` reads
                    them back through this registry)
``tracer``          :meth:`~mxtpu.observability.trace.Tracer.stats`
``flight``          :meth:`~mxtpu.observability.flight.FlightRecorder
                    .stats`
``kernel_invocations``  :func:`mxtpu.ops.pallas.counters.counts` —
                    trace-time Pallas kernel invocation counters
                    (``kernel_invocations.<kernel_name>``)
``lifecycle``       page-sanitizer shadow-accounting stats from the
                    serving-lifecycle pass (``lifecycle.armed``,
                    ``lifecycle.pages_tracked``,
                    ``lifecycle.violations_ever`` — see
                    ``analysis/lifecycle_check.py``)
==================  ====================================================

Live objects (engines, gateways, supervisors, routers) register with
:meth:`~MetricsRegistry.register_stats`, which accepts anything with a
``stats`` property/method; unregister when the object retires.  All
values are numbers (bools coerce to 0/1); non-numeric leaves and
non-string keys are skipped during flattening.

Determinism: a snapshot is plain host counters — two runs of the same
seed + fault plan produce identical deltas, which is what lets bench
records cite registry deltas as evidence instead of wall clocks.
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Dict, Iterable, List, Optional

__all__ = ["MetricsRegistry", "get_registry", "default_registry"]


def _flatten(prefix: str, value: Any, out: Dict[str, float]) -> None:
    if isinstance(value, dict):
        for k, v in value.items():
            if isinstance(k, str):
                _flatten(prefix + "." + k, v, out)
        return
    if isinstance(value, bool):
        out[prefix] = int(value)
    elif isinstance(value, (int, float)):
        out[prefix] = value
    # non-numeric leaves (status strings, error records) are observable
    # through the owning object's own API; the registry is numeric


class MetricsRegistry:
    """Named lazy sources -> one flat numeric snapshot (module
    docstring)."""

    def __init__(self):
        self._sources: Dict[str, Callable[[], dict]] = {}

    # -- registration ----------------------------------------------------
    def register_source(self, name: str, fn: Callable[[], dict],
                        replace: bool = False) -> None:
        """Register ``fn() -> nested dict`` under ``name``.  Sources
        evaluate lazily at snapshot time; a raising source contributes
        one ``<name>.source_error = 1`` key instead of killing the
        snapshot (telemetry must never take the service down)."""
        if name in self._sources and not replace:
            raise ValueError(
                "metrics source %r already registered (pass "
                "replace=True to swap it)" % (name,))
        if not callable(fn):
            raise TypeError("metrics source must be a callable "
                            "returning a dict, got %r" % (fn,))
        self._sources[name] = fn

    def register_stats(self, name: str, obj: Any,
                       replace: bool = False) -> None:
        """Register a live object exposing ``stats`` (property, method,
        or plain dict attribute) — engines, gateways, supervisors,
        routers."""
        if not hasattr(obj, "stats"):
            raise TypeError(
                "register_stats needs an object with a `stats` "
                "property/method, got %r" % (type(obj).__name__,))

        def _pull(o=obj):
            st = o.stats
            return st() if callable(st) else st

        self.register_source(name, _pull, replace=replace)

    def unregister(self, name: str) -> None:
        self._sources.pop(name, None)

    def sources(self) -> List[str]:
        return sorted(self._sources)

    # -- collection ------------------------------------------------------
    def snapshot(self, sources: Optional[Iterable[str]] = None
                 ) -> Dict[str, float]:
        """One flat ``{"source.key": number}`` dict over the selected
        (default: all) sources, pulled lazily now."""
        names = self.sources() if sources is None else list(sources)
        out: Dict[str, float] = {}
        for name in names:
            fn = self._sources.get(name)
            if fn is None:
                raise KeyError(
                    "unknown metrics source %r (registered: %r)"
                    % (name, self.sources()))
            try:
                val = fn()
            except Exception:  # noqa: BLE001 — a broken source must
                out[name + ".source_error"] = 1   # not kill telemetry
                continue
            _flatten(name, val if isinstance(val, dict) else
                     {"value": val}, out)
        return out

    def delta(self, before: Dict[str, float],
              after: Optional[Dict[str, float]] = None,
              include_zero: bool = False) -> Dict[str, float]:
        """``after - before`` per key (``after`` defaults to a fresh
        snapshot).  Keys absent from ``before`` count from 0; keys
        absent from ``after`` are dropped (their object retired)."""
        if after is None:
            after = self.snapshot()
        out = {}
        for k, v in after.items():
            d = v - before.get(k, 0)
            if d or include_zero:
                out[k] = d
        return out

    # -- exposition ------------------------------------------------------
    @staticmethod
    def _prom_name(key: str) -> str:
        return "mxtpu_" + re.sub(r"[^a-zA-Z0-9_]", "_", key)

    def to_prometheus(self,
                      snapshot: Optional[Dict[str, float]] = None) -> str:
        """Prometheus text exposition (all gauges — these are live
        counters/levels read at scrape time)."""
        snap = self.snapshot() if snapshot is None else snapshot
        lines = []
        for key in sorted(snap):
            name = self._prom_name(key)
            lines.append("# TYPE %s gauge" % name)
            val = snap[key]
            lines.append("%s %s" % (
                name, ("%d" % val) if isinstance(val, int)
                else repr(float(val))))
        return "\n".join(lines) + "\n"

    def to_json(self, snapshot: Optional[Dict[str, float]] = None,
                indent: Optional[int] = None) -> str:
        snap = self.snapshot() if snapshot is None else snapshot
        return json.dumps(snap, sort_keys=True,
                          separators=(",", ":"), indent=indent)


# -- built-in sources ----------------------------------------------------

def _src_resilience() -> dict:
    from ..resilience.counters import counters
    return counters()


def _src_compile_ledger() -> dict:
    from ..analysis.compile_ledger import get_ledger
    out: Dict[str, dict] = {}
    for site, s in get_ledger().stats().items():
        out[site] = {"programs": s["misses"], "hits": s["hits"],
                     "lookups": s["lookups"]}
    return out


def _src_engine_bulk() -> dict:
    from .. import engine
    return engine.bulk_stats()


def _src_profiler() -> dict:
    from .. import profiler
    return {k: v for k, v in profiler.counter_values().items()
            if isinstance(v, (int, float))}


def _src_tracer() -> dict:
    from .trace import get_tracer
    return get_tracer().stats()


def _src_flight() -> dict:
    from .flight import get_flight
    return get_flight().stats()


def _src_lifecycle() -> dict:
    """Page-sanitizer shadow-accounting stats from the lifecycle pass
    (``lifecycle.armed``, ``lifecycle.pages_tracked``,
    ``lifecycle.violations_ever`` ...) — all plain host ints, so a
    scrape never arms or perturbs the sanitizer
    (analysis/lifecycle_check.py)."""
    from ..analysis.lifecycle_check import get_sanitizer
    return get_sanitizer().stats()


def _src_kernel_invocations() -> dict:
    """Pallas kernel trace-time invocation counters: one bump per
    pallas_call traced into a compiled program, keyed by kernel name
    (``kernel_invocations.paged_attention`` etc.) — the counter that
    proves the fast path is actually riding the kernel, not the XLA
    fallback (ops/pallas/counters.py)."""
    from ..ops.pallas import counters
    return counters.counts()


def default_registry() -> MetricsRegistry:
    """A fresh registry pre-loaded with the built-in process-wide
    sources (module docstring table)."""
    reg = MetricsRegistry()
    reg.register_source("resilience", _src_resilience)
    reg.register_source("compile_ledger", _src_compile_ledger)
    reg.register_source("engine_bulk", _src_engine_bulk)
    reg.register_source("profiler", _src_profiler)
    reg.register_source("tracer", _src_tracer)
    reg.register_source("flight", _src_flight)
    reg.register_source("kernel_invocations", _src_kernel_invocations)
    reg.register_source("lifecycle", _src_lifecycle)
    return reg


_REGISTRY = default_registry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry (built-in sources pre-registered; add
    live engines/gateways with :meth:`MetricsRegistry.register_stats`)."""
    return _REGISTRY
