"""mxtpu.observability — unified observability: deterministic request
tracing, failure flight recorder, and one metrics registry across
serving and training (docs/observability.md).

Three modules, one discipline — counter clocks, never wall clocks, so
every trace, postmortem, and metrics delta is bit-reproducible under
the same seeds + fault plan and assertable in tier-1:

- :mod:`~mxtpu.observability.trace` — process-wide :class:`Tracer`
  (off by default; ``MXTPU_TRACE=1`` or :func:`tracing`): typed
  spans/events with tick timestamps and correlation ids threaded along
  the existing rid <-> tag maps, covering the full request path
  (gateway admit/QoS wait -> router dispatch -> transport -> engine
  admission/prefix-hit/COW/swap/deferral -> prefill chunks, decode
  steps, draft/verify windows -> terminal state) plus guardian events
  and automatic events from every fired ``resilience.faults`` site;
  Chrome trace-event export (:func:`export_chrome_trace`) serves the
  tick traces and the legacy ``mxtpu.profiler`` events through one
  writer, and spans wrap in ``jax.profiler.TraceAnnotation`` when a
  profiler session runs.
- :mod:`~mxtpu.observability.flight` — :class:`FlightRecorder`
  (``MXTPU_FLIGHT_BUFFER=N`` or :func:`flight_recording`): bounded
  per-request event rings that, on any failure path — quarantine,
  shed, replica death drain, guardian rollback, checkpoint corruption
  — snapshot the implicated requests' timelines plus a counters delta
  into deterministic, JSON-dumpable postmortems.
- :mod:`~mxtpu.observability.metrics` — one :class:`MetricsRegistry`
  with named lazy sources (engine/gateway/router/supervisor stats,
  resilience counters, guardian counters, CompileLedger per-site
  program counts, bulk-cache stats) flattened into a single snapshot
  with ``snapshot()``/``delta()`` and Prometheus-text + JSON
  exposition; ``tools/diagnose.py`` and ``bench.py`` collect through
  it.

Coverage is checked statically: the ``obs_check`` analysis pass (O001,
``python -m mxtpu.analysis obs``) asserts every declared fault site
resolves to a registered trace event type and every CompileLedger site
to a metrics key — observability is lost loudly, mirroring R005.
"""

from __future__ import annotations

from .flight import (FlightRecorder, Postmortem, flight_recording,
                     get_flight)
from .metrics import MetricsRegistry, default_registry, get_registry
from .trace import (EVENT_TYPES, TraceEvent, Tracer, export_chrome_trace,
                    gateway_rid, get_tracer, tracing)

__all__ = [
    "Tracer", "TraceEvent", "get_tracer", "tracing", "gateway_rid",
    "EVENT_TYPES", "export_chrome_trace",
    "FlightRecorder", "Postmortem", "get_flight", "flight_recording",
    "MetricsRegistry", "get_registry", "default_registry",
]
