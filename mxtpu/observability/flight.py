"""Failure flight recorder: bounded per-request ring buffers + postmortems.

A production failure report must carry the failing request's WHOLE
timeline — admitted where, routed where, which tier served its prefix,
which fault fired — without paying unbounded trace memory on the happy
path.  The flight recorder is the bounded always-on form of the tracer:
it attaches to the :class:`~mxtpu.observability.trace.Tracer` as a sink
(events flow even while full tracing is disabled), keeps only the last
``buffer`` events per request id in a ring, and on any failure path —
engine quarantine, load shed, replica death drain, guardian rollback,
checkpoint corruption — snapshots a :class:`Postmortem` naming the
implicated requests plus a resilience-counters DELTA (relative to the
recorder's reset, so reruns of the same seed + fault plan serialize
byte-identically; asserted in tests/test_observability.py).

Timelines materialize at READ time (:meth:`FlightRecorder.postmortem_
record` / :meth:`to_json`) from the live ring buffers: a replica-death
postmortem dumped after the run therefore shows the drained requests'
requeue ("reset") and re-dispatch events too, not just their history up
to the death — the ring bound is the only truncation, and it is
explicit (``MXTPU_FLIGHT_BUFFER`` events per request).

Enable with ``MXTPU_FLIGHT_BUFFER=N`` (ambient, N > 0 events per
request) or the :func:`flight_recording` context manager / ``get_
flight().enable()``.  Determinism: ticks come from the tracer's counter
clock; wall clocks never appear in a postmortem.
"""

from __future__ import annotations

import json
import os
import sys
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .trace import TraceEvent, get_tracer

__all__ = ["Postmortem", "FlightRecorder", "get_flight",
           "flight_recording"]

#: ring buffers kept for at most this many distinct request ids; the
#: oldest-touched id is evicted past it (bounded-bookkeeping discipline)
MAX_TRACKED_REQUESTS = 4096
#: postmortem records kept (oldest evicted past it)
MAX_POSTMORTEMS = 256


def default_buffer() -> int:
    """Ambient per-request ring size: ``MXTPU_FLIGHT_BUFFER`` (0 = the
    recorder stays off)."""
    try:
        return max(0, int(os.environ.get("MXTPU_FLIGHT_BUFFER", "0")))
    except ValueError:
        return 0


class Postmortem:
    """One failure snapshot: the trigger (kind, tick, context, counters
    delta) captured at failure time plus the implicated request ids
    whose timelines materialize from the ring buffers at read time.
    ``noise`` is the non-deterministic side channel (worker pids, wall
    clocks) — readable on the object and under ``include_noise=True``,
    excluded from the deterministic serialization like event noise."""

    __slots__ = ("kind", "tick", "rids", "context", "counters", "noise")

    def __init__(self, kind: str, tick: int, rids: Tuple[str, ...],
                 context: Dict[str, Any], counters: Dict[str, int],
                 noise: Optional[Dict[str, Any]] = None):
        self.kind = kind
        self.tick = tick
        self.rids = rids
        self.context = context
        self.counters = counters
        self.noise = dict(noise or {})

    def __repr__(self):
        return "<Postmortem %s tick=%d rids=%r>" % (
            self.kind, self.tick, list(self.rids))


class FlightRecorder:
    """Bounded per-request event rings + failure postmortems (module
    docstring)."""

    def __init__(self, buffer: Optional[int] = None):
        self._buffer = default_buffer() if buffer is None else int(buffer)
        self._rings: Dict[str, deque] = {}
        self._posts: List[Postmortem] = []
        self._counter_base: Dict[str, int] = {}
        self._attached = False
        if self._buffer > 0:
            self.enable(reset=True)

    # -- lifecycle -------------------------------------------------------
    @property
    def active(self) -> bool:
        return self._attached

    @property
    def buffer(self) -> int:
        return self._buffer

    def enable(self, buffer: Optional[int] = None,
               reset: bool = True) -> "FlightRecorder":
        if buffer is not None:
            self._buffer = int(buffer)
        if self._buffer <= 0:
            raise ValueError(
                "flight recorder needs a positive per-request buffer "
                "(set MXTPU_FLIGHT_BUFFER or pass buffer=)")
        if reset:
            self.reset()
        if not self._attached:
            get_tracer().add_sink(self)
            self._attached = True
        return self

    def disable(self) -> None:
        if self._attached:
            get_tracer().remove_sink(self)
            self._attached = False

    def reset(self) -> None:
        """Clear rings and postmortems and re-baseline the counters
        snapshot — the start-of-run point postmortem determinism is
        relative to."""
        self._rings = {}
        self._posts = []
        self._counter_base = self._counters_now()

    # -- the tracer sink -------------------------------------------------
    def observe(self, ev: TraceEvent) -> None:
        """Called by the tracer for every emitted event (rid-less events
        land in a shared ``_global`` ring so pool-level context —
        replica deaths, spilled chains — survives into postmortems)."""
        rid = ev.rid if ev.rid is not None else "_global"
        ring = self._rings.get(rid)
        if ring is None:
            if len(self._rings) >= MAX_TRACKED_REQUESTS:
                # evict the least-recently-touched id (insertion order
                # approximates it; dict preserves insertion order and a
                # touched ring is re-inserted below)
                self._rings.pop(next(iter(self._rings)))
            ring = deque(maxlen=self._buffer)
        else:
            del self._rings[rid]     # re-insert = touch
        ring.append(ev)
        self._rings[rid] = ring

    # -- failure capture -------------------------------------------------
    @staticmethod
    def _counters_now() -> Dict[str, int]:
        # Bootstrap guard: the ambient recorder is constructed at
        # module import (MXTPU_FLIGHT_BUFFER), and importing
        # mxtpu.resilience from here would circle back into this
        # still-executing module (guardian imports it).  Only read
        # counters from an ALREADY-imported module — before
        # mxtpu.resilience.counters exists, every counter is zero
        # (its module holds the only writers), so the empty baseline
        # is exact, not approximate.
        mod = sys.modules.get("mxtpu.resilience.counters")
        if mod is None:
            return {}
        return mod.counters()

    def failure(self, kind: str, rids=(), noise=None,
                **context) -> Optional[Postmortem]:
        """Record one postmortem (no-op while inactive).  ``rids`` are
        correlation ids (resolved through the tracer's alias map);
        ``context`` must be JSON-able, deterministic host data —
        replica ids, site names, error TYPE names (never wall clocks or
        memory addresses).  Non-deterministic facts worth keeping (a
        dead worker's pid) go in ``noise=``: present on the Postmortem
        and under ``include_noise=True``, excluded from the
        deterministic serialization."""
        if not self._attached:
            return None
        tr = get_tracer()
        now = self._counters_now()
        delta = {k: now[k] - self._counter_base.get(k, 0)
                 for k in sorted(now)
                 if now[k] - self._counter_base.get(k, 0)}
        pm = Postmortem(
            kind=kind,
            tick=tr.ticks,
            rids=tuple(tr.resolve(r) for r in rids),
            context=dict(context),
            counters=delta,
            noise=noise)
        if len(self._posts) >= MAX_POSTMORTEMS:
            self._posts.pop(0)
        self._posts.append(pm)
        return pm

    # -- reading ---------------------------------------------------------
    @property
    def postmortems(self) -> List[Postmortem]:
        return list(self._posts)

    def timeline(self, rid: str) -> List[TraceEvent]:
        """The ring-buffered timeline of one request id (resolved
        through the tracer alias map)."""
        rid = get_tracer().resolve(rid)
        return list(self._rings.get(rid, ()))

    def postmortem_record(self, pm: Postmortem,
                          include_noise: bool = False) -> Dict[str, Any]:
        """Materialize one postmortem into a JSON-able record: trigger
        context + counters delta + each implicated request's CURRENT
        ring-buffered timeline (read-time materialization — see module
        docstring)."""
        rec = {
            "kind": pm.kind,
            "tick": pm.tick,
            "context": pm.context,
            "counters": pm.counters,
            "requests": {
                rid: [e.to_dict(include_noise=include_noise)
                      for e in self.timeline(rid)]
                for rid in pm.rids},
        }
        if include_noise and pm.noise:
            rec["noise"] = pm.noise
        return rec

    def stats(self) -> Dict[str, int]:
        """Numeric summary (a MetricsRegistry source)."""
        return {
            "active": int(self._attached),
            "buffer": self._buffer,
            "tracked_requests": len(self._rings),
            "postmortems": len(self._posts),
        }

    def to_json(self, include_noise: bool = False,
                indent: Optional[int] = None) -> str:
        """Deterministic JSON of every postmortem (byte-identical
        across reruns of the same seed + fault plan after a reset —
        the flight-recorder acceptance contract)."""
        return json.dumps(
            {"version": 1, "clock": "tick", "buffer": self._buffer,
             "postmortems": [self.postmortem_record(
                 pm, include_noise=include_noise)
                 for pm in self._posts]},
            sort_keys=True, separators=(",", ":"), indent=indent)


class _FlightContext:
    """``with flight_recording(N):`` — enable (resetting), restore the
    prior attached state AND buffer size on exit, so a scoped recording
    inside a process started with ambient ``MXTPU_FLIGHT_BUFFER`` does
    not silently switch off (or resize) the always-on recorder (the
    same restore discipline as ``tracing()``).  The enter-time reset is
    not undone — the ambient recorder resumes with the events recorded
    since."""

    def __init__(self, buffer: int):
        self._buffer = buffer
        self._prev: Optional[Tuple[bool, int]] = None

    def __enter__(self) -> FlightRecorder:
        fl = get_flight()
        self._prev = (fl.active, fl.buffer)
        return fl.enable(buffer=self._buffer, reset=True)

    def __exit__(self, *exc):
        fl = get_flight()
        prev_attached, prev_buffer = self._prev
        fl.disable()
        fl._buffer = prev_buffer
        if prev_attached:
            fl.enable(buffer=prev_buffer, reset=False)
        return False


def flight_recording(buffer: int = 256) -> _FlightContext:
    """Scoped flight recording: ``with flight_recording(256) as fl:``."""
    return _FlightContext(buffer)


_FLIGHT = FlightRecorder()


def get_flight() -> FlightRecorder:
    """The process-wide flight recorder (attached at import when
    ``MXTPU_FLIGHT_BUFFER`` > 0)."""
    return _FLIGHT
