"""Deterministic structured tracing: typed spans/events on a tick clock.

The reference MXNet's engine-integrated profiler stamps every engine op
with wall-clock timestamps and emits chrome://tracing JSON.  At serving
scale the question a trace must answer — "which replica/tier/fault ate
my latency?" — has to be answerable from telemetry that REPLAYS: this
tracer therefore stamps every event with a process-wide COUNTER tick,
never a wall clock, so the trace of a seeded run under a fault plan is
bit-reproducible and assertable in tier-1 (the same discipline as
``mxtpu.resilience.faults``).  Optional wall-clock annotations ride in
a separate ``noise`` payload that is NOISE-labeled and excluded from
the deterministic serialization.

Off by default.  Enable with ``MXTPU_TRACE=1`` (ambient, read once at
tracer construction) or the :func:`tracing` context manager.  When the
:mod:`mxtpu.profiler` session is running (``profiler.start()``), every
span additionally wraps itself in a ``jax.profiler.TraceAnnotation`` so
host-side spans land inside the XLA trace.

Event taxonomy (:data:`EVENT_TYPES`): every event carries a registered
type — an unregistered type raises at the emit site, and the
``obs_check`` analysis pass (O001, docs/analysis.md) cross-checks that
every declared fault site in ``resilience.faults.SITES`` has its
``fault.<site>`` type registered here, so observability coverage is
lost loudly, never silently.

Correlation ids: events carry an optional ``rid`` string threaded along
the existing rid <-> tag maps — engines emit ``"<tag>:<rid>"`` (tag =
``ledger_tag`` or ``"eng"``; replica pools stamp the replica id), the
gateway emits ``"gw:<rid>"``, and the transport registers an ALIAS from
the engine id to the gateway id at submit, so one request's events from
every layer assemble into one :meth:`Tracer.timeline`.

Determinism contract: with the tracer reset at the start of a run, the
same seeds + the same ``MXTPU_FAULT_PLAN`` produce a byte-identical
:meth:`Tracer.to_json` (asserted in tests/test_observability.py), and
tracing compiles ZERO additional programs — every emit is host-side
bookkeeping (asserted via the compile ledger).

This module must stay import-light (no jax at import time): the serving
and resilience hot paths import it unconditionally.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

__all__ = ["TraceEvent", "Tracer", "get_tracer", "tracing",
           "gateway_rid", "EVENT_TYPES", "export_chrome_trace"]


#: alias entries (engine-rid -> gateway-rid) kept for at most this many
#: child ids; the oldest-registered is evicted past it.  One alias lands
#: per submitted request, so the always-on serving posture (ambient
#: MXTPU_FLIGHT_BUFFER, tracer never reset) would otherwise grow the
#: map without bound — the same bounded-bookkeeping discipline as the
#: flight recorder's request rings.
MAX_ALIASES = 8192

#: the registered span/event taxonomy: type -> one-line description
#: (docs/observability.md mirrors this table).  ``fault.<site>`` types
#: are declared EXPLICITLY (not derived from ``faults.SITES``) so the
#: O001 cross-check can catch a site added without its event type.
EVENT_TYPES: Dict[str, str] = {
    # -- gateway (mxtpu.serving.gateway) --------------------------------
    "gateway.admit": "request accepted into the gateway queue (QoS "
                     "class, tenant); queue wait = dispatch tick delta",
    "gateway.shed": "request shed (QoS overflow / quota / engine shed)",
    "gateway.dispatch": "request dispatched to a replica (gen, replica, "
                        "wait_ticks)",
    "gateway.hedge": "hedged duplicate dispatch fired",
    "gateway.requeue": "dispatch lost (replica death/stall) — stream "
                       "reset, request requeued at class front",
    "gateway.expired": "tick deadline passed; finished with partial "
                       "stream",
    "gateway.finish": "terminal gateway status (ok/failed)",
    "gateway.pump": "one gateway service iteration (span)",
    # -- router / transport ---------------------------------------------
    "router.dispatch": "replica selected (locality score, chosen "
                       "replica, load)",
    "transport.submit": "spec handed to a replica engine (aliases the "
                        "engine rid to the gateway rid)",
    "transport.worker_spawn": "subprocess replica worker started and "
                              "completed its init handshake (pid is "
                              "noise — see docs/serving.md)",
    "transport.worker_exit": "subprocess replica worker left the pool "
                             "(graceful shutdown, kill, or reaped "
                             "death; exit code when reapable)",
    "transport.rpc_timeout": "a replica RPC exhausted its tick budget "
                             "(method, ticks) — counted toward "
                             "replica death as a transport failure",
    "replica.death": "supervisor declared a replica dead "
                     "(drain-and-requeue)",
    "replica.revive": "probation over — replica re-admitted (a "
                      "subprocess replica respawned a fresh worker "
                      "first)",
    # -- elastic serving (mxtpu.serving.autoscale) ----------------------
    "autoscale.decision": "one autoscaler policy evaluation that acted "
                          "(direction, shed delta, queue depth, pool "
                          "size)",
    "autoscale.spawn": "autoscaler grew the pool by one replica (or "
                       "failed to — error field; capacity unchanged)",
    "autoscale.retire": "graceful scale-down lifecycle (stage: begin/"
                        "released/reopened) — the victim drains at "
                        "stream completion, never the death path",
    "serving.adopt": "live weight hot-swap lifecycle (stage: staged/"
                     "installed/failed) — new param generation adopted "
                     "at an iteration boundary",
    "serving.rollback": "previous param generation re-staged "
                        "(hot-swap rollback)",
    # -- engines (mxtpu.parallel.serving) -------------------------------
    "engine.iteration": "one engine scheduler iteration (span)",
    "engine.admit": "admission started (prompt tokens)",
    "engine.prefix_hit": "radix/host-tier prefix hit (tokens, pages "
                         "shared — prefill skipped)",
    "engine.cow": "copy-on-write page clone at the divergence point",
    "engine.swap_in": "host-tier chain restored at admission (pages)",
    "engine.swap_out": "pinned chain spilled to the host tier (pages; "
                       "dropped=True when the copy was abandoned)",
    "engine.defer": "admission deferred on transient page exhaustion",
    "engine.prefill_chunk": "one chunked-prefill program ran for a "
                            "prefilling slot",
    "engine.decode": "slot emitted one token in the pooled decode step",
    "engine.draft": "speculative proposal drafted for a slot",
    "engine.verify": "slot scored in the pooled batched-verify call "
                     "(drafted, accepted)",
    "engine.finish": "request terminal in the engine "
                     "(ok/failed/expired/cancelled)",
    "engine.quarantine": "per-slot failure contained (site, error)",
    "engine.requeue": "quarantined request re-queued (retries left)",
    "engine.shed": "submission shed (typed LoadShedError)",
    "engine.cancel": "request cancelled through the idempotent release "
                     "path",
    # -- guardian (mxtpu.resilience.guardian) ---------------------------
    "guardian.skip": "non-finite step contained (update gated off)",
    "guardian.spike": "finite loss spike detected -> rollback",
    "guardian.rollback": "restored the last verified checkpoint",
    "guardian.checkpoint": "verified checkpoint written",
    "guardian.window": "one fused N-step window dispatched (the "
                       "once-per-N host sync)",
    # -- profiler parity API (mxtpu.profiler) ---------------------------
    "profiler.counter": "profiler.Counter value change",
    "profiler.marker": "profiler.Marker instant",
    # -- automatic fault events (every resilience.faults site) ----------
    # one type per DECLARED site; a plan firing at an undeclared
    # (test-private) site emits fault.unregistered with a site field
    "fault.serving.step": "injected fault fired at serving.step",
    "fault.serving.admit": "injected fault fired at serving.admit",
    "fault.serving.prefix_lookup":
        "injected fault fired at serving.prefix_lookup",
    "fault.serving.block_alloc":
        "injected fault fired at serving.block_alloc",
    "fault.serving.swap_out": "injected fault fired at serving.swap_out",
    "fault.serving.swap_in": "injected fault fired at serving.swap_in",
    "fault.serving.draft": "injected fault fired at serving.draft",
    "fault.serving.verify": "injected fault fired at serving.verify",
    "fault.gateway.admit": "injected fault fired at gateway.admit",
    "fault.router.dispatch": "injected fault fired at router.dispatch",
    "fault.replica.health": "injected fault fired at replica.health",
    "fault.replica.stream": "injected fault fired at replica.stream",
    "fault.transport.rpc": "injected fault fired at transport.rpc",
    "fault.transport.encode":
        "injected fault fired at transport.encode",
    "fault.transport.worker_death":
        "injected fault fired at transport.worker_death",
    "fault.kvstore.reduce": "injected fault fired at kvstore.reduce",
    "fault.checkpoint.save": "injected fault fired at checkpoint.save",
    "fault.engine.flush": "injected fault fired at engine.flush",
    "fault.guardian.check": "injected fault fired at guardian.check",
    "fault.ckpt.write": "injected fault fired at ckpt.write",
    "fault.ckpt.verify": "injected fault fired at ckpt.verify",
    "fault.autoscale.spawn":
        "injected fault fired at autoscale.spawn",
    "fault.autoscale.retire":
        "injected fault fired at autoscale.retire",
    "fault.serving.adopt": "injected fault fired at serving.adopt",
    "fault.unregistered": "injected fault fired at a site with no "
                          "declared event type (site in fields)",
}


class TraceEvent(NamedTuple):
    """One recorded event.  ``tick`` is the deterministic counter clock
    (one tick per recorded event); ``phase`` is ``"I"`` (instant),
    ``"B"``/``"E"`` (span begin/end); ``noise`` holds wall-clock or
    otherwise non-deterministic annotations, excluded from the
    deterministic serialization."""

    tick: int
    etype: str
    rid: Optional[str]
    phase: str
    fields: Dict[str, Any]
    noise: Dict[str, Any]

    def to_dict(self, include_noise: bool = False) -> Dict[str, Any]:
        d: Dict[str, Any] = {"tick": self.tick, "type": self.etype,
                             "phase": self.phase}
        if self.rid is not None:
            d["rid"] = self.rid
        if self.fields:
            d["fields"] = self.fields
        if include_noise and self.noise:
            d["noise"] = self.noise
        return d


def gateway_rid(tag) -> str:
    """Correlation id of a gateway request from its dispatch tag: the
    gateway tags replica submissions ``(rid, dispatch_gen)`` — every
    generation of one request shares ONE timeline."""
    if isinstance(tag, tuple) and tag:
        return "gw:%s" % (tag[0],)
    return "gw:%s" % (tag,)


class _Span:
    """Begin/end event pair; on-profiler runs additionally wrap the
    region in a ``jax.profiler.TraceAnnotation`` so the host span lands
    inside the XLA trace."""

    __slots__ = ("_tr", "_etype", "_rid", "_fields", "_ann", "_t0")

    def __init__(self, tracer, etype, rid, fields):
        self._tr = tracer
        self._etype = etype
        self._rid = rid
        self._fields = fields
        self._ann = None
        self._t0 = None

    def __enter__(self):
        self._ann = _profiler_annotation(self._etype)
        if self._ann is not None:
            self._ann.__enter__()
        self._tr.emit(self._etype, rid=self._rid, phase="B",
                      **self._fields)
        if self._tr.record_wall:
            import time
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        noise = None
        if self._t0 is not None:
            import time
            noise = {"wall_s": time.perf_counter() - self._t0}
        self._tr.emit(self._etype, rid=self._rid, phase="E",
                      noise=noise)
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        return False


def _profiler_annotation(name):
    """A jax TraceAnnotation when (and only when) a profiler session is
    running — the only place this module touches jax, and only on an
    already-active trace session."""
    try:
        from .. import profiler as _prof
        if _prof.state() != "run":
            return None
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — tracing must never take the
        return None    # serving path down over a profiler hiccup


def _env_truthy(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "off", "no")


class Tracer:
    """Process-wide structured tracer (module docstring).

    ``max_events`` bounds the in-memory trace (further events are
    counted in ``dropped_events``, never silently lost from the
    counters); flight-recorder sinks observe every event regardless, so
    their bounded ring buffers stay current past the cap.
    """

    def __init__(self, max_events: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self._lock = threading.RLock()
        self._enabled = (_env_truthy("MXTPU_TRACE") if enabled is None
                         else bool(enabled))
        if max_events is None:
            try:
                max_events = int(os.environ.get("MXTPU_TRACE_EVENTS",
                                                200000))
            except ValueError:
                max_events = 200000
        self._max_events = int(max_events)
        self.record_wall = _env_truthy("MXTPU_TRACE_WALL")
        self._events: List[TraceEvent] = []
        self._profiler_events: List[Tuple[int, str, str, float]] = []
        self._alias: Dict[str, str] = {}
        self._tick = 0
        self._dropped = 0
        self._sinks: List[Any] = []   # flight recorders

    # -- lifecycle -------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def active(self) -> bool:
        """Whether emit() records anywhere (the tracer proper OR an
        attached flight-recorder sink) — the cheap guard every
        instrumented hot path checks first."""
        return self._enabled or bool(self._sinks)

    def enable(self, reset: bool = True) -> None:
        with self._lock:
            if reset:
                self.reset()
            self._enabled = True

    def disable(self) -> None:
        with self._lock:
            self._enabled = False

    def reset(self) -> None:
        """Clear events, the tick clock, aliases, and the profiler
        channel — the start-of-run point the determinism contract is
        relative to."""
        with self._lock:
            self._events = []
            self._profiler_events = []
            self._alias = {}
            self._tick = 0
            self._dropped = 0

    # -- sinks (flight recorder) -----------------------------------------
    def add_sink(self, sink) -> None:
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    # -- correlation -----------------------------------------------------
    def alias(self, child: str, parent: str) -> None:
        """Register ``child`` as another name of ``parent``'s timeline
        (the transport's engine-rid -> gateway-rid mapping): events
        emitted under ``child`` resolve to ``parent`` at record time."""
        with self._lock:
            if (child not in self._alias
                    and len(self._alias) >= MAX_ALIASES):
                self._alias.pop(next(iter(self._alias)))
            self._alias[child] = parent

    def resolve(self, rid: Optional[str]) -> Optional[str]:
        if rid is None:
            return None
        return self._alias.get(rid, rid)

    # -- recording -------------------------------------------------------
    def emit(self, etype: str, rid: Optional[str] = None,
             phase: str = "I", noise: Optional[dict] = None,
             **fields) -> Optional[TraceEvent]:
        """Record one typed event (no-op unless :attr:`active`).
        ``etype`` must be registered in :data:`EVENT_TYPES` — a typo
        here is a taxonomy bug and raises."""
        if not (self._enabled or self._sinks):
            return None
        if etype not in EVENT_TYPES:
            raise ValueError(
                "unregistered trace event type %r — add it to "
                "mxtpu.observability.trace.EVENT_TYPES (the obs_check "
                "pass cross-checks the taxonomy)" % (etype,))
        with self._lock:
            rid = self._alias.get(rid, rid) if rid is not None else None
            self._tick += 1
            ev = TraceEvent(self._tick, etype, rid, phase,
                            fields, noise or {})
            if self._enabled:
                if len(self._events) < self._max_events:
                    self._events.append(ev)
                else:
                    self._dropped += 1
            for sink in self._sinks:
                sink.observe(ev)
            return ev

    def span(self, etype: str, rid: Optional[str] = None,
             **fields) -> _Span:
        """Context manager recording a begin/end event pair (and a
        ``jax.profiler.TraceAnnotation`` when a profiler session is
        running)."""
        return _Span(self, etype, rid, fields)

    # -- the profiler parity channel -------------------------------------
    def profiler_event(self, name: str, wall_s: float = 0.0,
                       kind: str = "scope") -> None:
        """Record one explicit profiler-API event (Task/Frame/Event
        scopes, Markers).  Unlike trace events this channel is ALWAYS
        recorded — the user called the profiler API explicitly — but
        its wall durations are NOISE by nature and excluded from the
        deterministic trace serialization."""
        with self._lock:
            self._tick += 1
            if len(self._profiler_events) < self._max_events:
                self._profiler_events.append(
                    (self._tick, kind, name, float(wall_s)))

    def profiler_events(self) -> List[Tuple[int, str, str, float]]:
        with self._lock:
            return list(self._profiler_events)

    def clear_profiler_events(self) -> None:
        with self._lock:
            self._profiler_events = []

    # -- querying --------------------------------------------------------
    def events(self, rid: Optional[str] = None,
               types=None) -> List[TraceEvent]:
        with self._lock:
            out = list(self._events)
        if rid is not None:
            out = [e for e in out if e.rid == self.resolve(rid)]
        if types is not None:
            tset = {types} if isinstance(types, str) else set(types)
            out = [e for e in out if e.etype in tset]
        return out

    def timeline(self, rid: str) -> List[TraceEvent]:
        """Every recorded event of one request, tick order."""
        return self.events(rid=rid)

    def span_count(self) -> int:
        """Completed spans (end events) recorded so far."""
        with self._lock:
            return sum(1 for e in self._events if e.phase == "E")

    @property
    def ticks(self) -> int:
        """The current tick — cheap; ``stats()`` scans the whole event
        list, which failure-path callers must not pay per postmortem."""
        with self._lock:
            return self._tick

    @property
    def dropped_events(self) -> int:
        return self._dropped

    def stats(self) -> Dict[str, int]:
        """Numeric summary (a MetricsRegistry source)."""
        with self._lock:
            return {
                "enabled": int(self._enabled),
                "events": len(self._events),
                "spans": sum(1 for e in self._events
                             if e.phase == "E"),
                "dropped_events": self._dropped,
                "profiler_events": len(self._profiler_events),
                "ticks": self._tick,
                "aliases": len(self._alias),
            }

    # -- serialization ---------------------------------------------------
    def to_json(self, include_noise: bool = False,
                indent: Optional[int] = None) -> str:
        """Deterministic JSON of the recorded trace: same seeds + same
        fault plan (+ a reset at the start of the run) => byte-identical
        output.  ``include_noise=True`` adds the NOISE-labeled
        wall-clock annotations (then equality is no longer promised)."""
        with self._lock:
            events = [e.to_dict(include_noise=include_noise)
                      for e in self._events]
            dropped = self._dropped
        return json.dumps({"version": 1, "clock": "tick",
                           "dropped": dropped, "events": events},
                          sort_keys=True, separators=(",", ":"),
                          indent=indent)


class _TracingContext:
    """``with tracing():`` — enable (resetting by default), restore the
    prior enabled state on exit."""

    def __init__(self, reset: bool = True):
        self._reset = reset
        self._prev = None

    def __enter__(self) -> Tracer:
        tr = get_tracer()
        self._prev = tr.enabled
        tr.enable(reset=self._reset)
        return tr

    def __exit__(self, *exc):
        if not self._prev:
            get_tracer().disable()
        return False


def tracing(reset: bool = True) -> _TracingContext:
    """Scoped tracing: ``with tracing() as tr: ... tr.to_json()``."""
    return _TracingContext(reset=reset)


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer instance."""
    return _TRACER


# -- chrome trace-event export (one writer for both APIs) ----------------

def export_chrome_trace(file=None, include_noise: bool = True,
                        tracer: Optional[Tracer] = None) -> Optional[str]:
    """Chrome trace-event JSON (chrome://tracing / Perfetto) serving
    BOTH the tick-clock structured trace and the legacy
    ``mxtpu.profiler`` Counter/Marker/scope events through one writer
    (the reference profiler's output format, on the deterministic
    clock: 1 tick is rendered as 1 us).  ``file`` may be a path or a
    writable file object; with neither, the JSON string is returned."""
    tr = tracer if tracer is not None else get_tracer()
    tid_map: Dict[str, int] = {}

    def _tid(rid):
        if rid is None:
            return 0
        return tid_map.setdefault(rid, len(tid_map) + 1)

    trace_events: List[dict] = []
    for ev in tr.events():
        ph = {"I": "i", "B": "B", "E": "E"}[ev.phase]
        rec = {"name": ev.etype, "ph": ph, "ts": ev.tick, "pid": 0,
               "tid": _tid(ev.rid), "cat": "mxtpu"}
        if ph == "i":
            rec["s"] = "t"
        args = dict(ev.fields)
        if ev.rid is not None:
            args["rid"] = ev.rid
        if include_noise and ev.noise:
            args["NOISE"] = dict(ev.noise)
        rec["args"] = args
        trace_events.append(rec)
    for (tick, kind, name, wall_s) in tr.profiler_events():
        trace_events.append({
            "name": name, "ph": "X", "ts": tick,
            "dur": max(1, int(wall_s * 1e6)),
            "pid": 0, "tid": 0,
            "cat": "profiler,NOISE-wall-duration",
            "args": {"kind": kind, "wall_s": wall_s},
        })
    # the profiler parity API's counters, as chrome counter samples
    try:
        from .. import profiler as _prof
        now_tick = tr.ticks
        for name, val in sorted(_prof.counter_values().items()):
            if isinstance(val, (int, float)):
                trace_events.append({
                    "name": name, "ph": "C", "ts": now_tick,
                    "pid": 0, "tid": 0, "cat": "profiler",
                    "args": {"value": val}})
    except Exception:  # noqa: BLE001 — export must not die on a
        pass           # profiler import problem

    doc = {"traceEvents": trace_events, "displayTimeUnit": "ms",
           "otherData": {"clock": "mxtpu deterministic tick "
                                  "(1 tick rendered as 1 us)"}}
    text = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    if file is None:
        return text
    if hasattr(file, "write"):
        file.write(text)
        return None
    with open(file, "w") as f:
        f.write(text)
    return None
