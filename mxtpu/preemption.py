"""Preemption-signal checkpointing (SURVEY §5 failure-detection row: the
reference has no elastic recovery — checkpoint-restart is the story, and
the TPU build adds the missing piece: a SIGTERM hook that saves state
before the host is reclaimed).

TPU VMs (and most batch schedulers) deliver SIGTERM with a grace window
before preemption.  ``install()`` registers a handler that (a) marks the
flag so training loops can drain cleanly via ``preempted()``, and
(b) runs the supplied save callback once, immediately, in the main
thread (Python signal handlers execute between bytecodes — jax arrays
are immutable values, so saving mid-step reads a consistent snapshot).
"""

from __future__ import annotations

import logging
import signal
import threading

from .resilience.faults import inject as _inject

__all__ = ["install", "uninstall", "preempted", "reset",
           "PreemptionCheckpointHandler", "restore_latest"]

_lock = threading.Lock()
_state = {"flag": False, "save_fn": None, "prev": {}, "signals": ()}


def _handler(signum, frame):
    # NO lock here: signal handlers run in the main thread between
    # bytecodes, and the main thread may already hold _lock inside
    # install()/uninstall() — acquiring it would self-deadlock exactly
    # when the grace window matters.  Plain dict reads/writes are atomic
    # under the GIL, which is all the consistency this needs.
    already = _state["flag"]
    _state["flag"] = True
    save_fn = _state["save_fn"]
    if already:
        return
    logging.warning("preemption signal %s received — checkpointing",
                    signal.Signals(signum).name)
    if save_fn is not None:
        try:
            save_fn()
        except Exception:
            logging.exception("preemption checkpoint failed")


def _wrap_save(save_fn, retry):
    """Route the save through the ``checkpoint.save`` fault-injection
    site and (optionally) a RetryPolicy — a flaky checkpoint target
    inside the SIGTERM grace window is exactly when a bounded retry
    earns its keep."""
    def attempt():
        _inject("checkpoint.save")
        return save_fn()

    if retry is None:
        return attempt
    return lambda: retry.call(attempt)


def install(save_fn, signals=(signal.SIGTERM,), retry=None):
    """Install the preemption hook.  save_fn() is called once on the
    first signal; training loops may also poll preempted().  ``retry``:
    optional :class:`mxtpu.resilience.RetryPolicy` applied to the save
    (transient checkpoint-write failures re-attempt inside the grace
    window; exhaustion is logged, never propagated out of the signal
    handler)."""
    with _lock:
        uninstall_locked()
        _state["save_fn"] = _wrap_save(save_fn, retry)
        _state["signals"] = tuple(signals)
        _state["flag"] = False
        for sig in signals:
            _state["prev"][sig] = signal.signal(sig, _handler)


def uninstall_locked():
    for sig, prev in _state["prev"].items():
        try:
            signal.signal(sig, prev)
        except (ValueError, OSError):
            pass
    _state["prev"] = {}
    _state["save_fn"] = None


def uninstall():
    with _lock:
        uninstall_locked()


def preempted() -> bool:
    return _state["flag"]


def reset():
    with _lock:
        _state["flag"] = False


def restore_latest(model_prefix, net, trainer=None):
    """Restore the newest VERIFIED preemption checkpoint written by
    :class:`PreemptionCheckpointHandler` under ``model_prefix``.

    Walks the rotated generations newest → oldest
    (``-preempt.params[.N]``), verifies each params (+ states, when a
    trainer is given) pair against its CRC manifest, and loads the first
    intact pair — a truncated, bit-flipped, or missing file falls back
    to the previous good generation (counted in
    ``resilience.counters()['ckpt_fallbacks']``).  The states file is
    matched to its params by the save-event token both manifests carry,
    not by suffix: a crash between the pair's two commit renames leaves
    suffix-aligned files from different save events (each CRC-clean),
    and token matching makes that torn pair fall back to the newest
    consistent one instead of silently loading new weights with stale
    optimizer state.  Returns the
    generation index loaded (0 = the most recent save); raises
    :class:`~mxtpu.resilience.CorruptCheckpointError` when no generation
    survives."""
    from .resilience import checkpoint as _ckpt
    from .resilience.counters import bump

    import os

    pfile = "%s-preempt.params" % model_prefix
    sfile = "%s-preempt.states" % model_prefix
    # scan generations independently: a deleted NEWEST file must not
    # hide the intact older ones behind it (the missing-file case of the
    # corruption matrix falls back like any other damage).  A generation
    # is a candidate only if some trace of it exists on disk — a payload
    # or a manifest — so a prefix with no checkpoints at all reports
    # "none present" rather than a phantom corrupt generation 0.
    candidates = []
    for g in range(max(64, _ckpt.default_keep())):
        suffix = "" if g == 0 else ".%d" % g
        paths = (pfile + suffix, pfile + suffix + _ckpt.MANIFEST_SUFFIX)
        if any(os.path.exists(p) for p in paths):
            candidates.append(g)
    if not candidates:
        raise _ckpt.CorruptCheckpointError(
            "no preemption checkpoint under prefix %r (no generation "
            "present — never saved, or the prefix is wrong)"
            % model_prefix)
    def _states_for(psuffix):
        """The states file belonging to the params generation at
        ``psuffix``.  The pair is matched by the shared save-event token
        the handler stamps into both manifests — the two files commit
        with separate renames, so a crash between them leaves suffix
        "aligned" files from DIFFERENT saves, each individually
        CRC-clean; token matching finds the states file that was really
        written alongside these params, whatever suffix rotation left it
        at.  Tokenless checkpoints (written before stamping) fall back
        to suffix-aligned pairing."""
        token = _ckpt.save_event(pfile + psuffix)
        if token is None:
            return sfile + psuffix
        for g2 in range(max(64, _ckpt.default_keep())):
            cand = sfile + ("" if g2 == 0 else ".%d" % g2)
            if os.path.exists(cand) and _ckpt.save_event(cand) == token:
                return cand
        raise _ckpt.CorruptCheckpointError(
            "no states file carries save event %s — torn pair from a "
            "crash between the params and states commits" % token,
            path=pfile + psuffix)

    last_err = None
    for g in candidates:
        suffix = "" if g == 0 else ".%d" % g
        try:
            fns = (pfile + suffix,)
            if trainer is not None:
                fns = (pfile + suffix, _states_for(suffix))
            for fn in fns:
                # cheap pre-checks only (existence + manifest presence,
                # the required=True contract) — the load paths below do
                # the ONE verified read each; a full CRC pass here would
                # double restore I/O on a multi-GB checkpoint
                if not os.path.exists(fn):
                    raise _ckpt.CorruptCheckpointError(
                        "checkpoint file missing", path=fn)
                if not _ckpt.has_manifest(fn):
                    raise _ckpt.CorruptCheckpointError(
                        "checkpoint has no manifest (%s sidecar missing) "
                        "but verification was required"
                        % _ckpt.MANIFEST_SUFFIX, path=fn)
            net.load_parameters(fns[0])
            if trainer is not None:
                trainer.load_states(fns[1])
            return g
        except _ckpt.CorruptCheckpointError as e:
            logging.warning("preemption restore: generation %d unusable "
                            "(%s) — falling back", g, e)
            bump("ckpt_fallbacks")
            last_err = e
    raise _ckpt.CorruptCheckpointError(
        "no verified preemption checkpoint under prefix %r (%d generation"
        "(s) present, all damaged or incomplete%s)"
        % (model_prefix, len(candidates),
           "; last error: %s" % last_err if last_err else ""))


class PreemptionCheckpointHandler:
    """Estimator event handler: saves parameters + trainer states on
    preemption and stops the fit loop at the next batch boundary
    (plugs into gluon.contrib.estimator alongside CheckpointHandler).

    Also a context manager: ``__exit__`` always uninstalls the SIGTERM
    hook, so an exception inside the fit loop cannot leak the handler
    into unrelated later code (the event-handler API — ``batch_end`` /
    ``train_end`` — keeps working unchanged)::

        with PreemptionCheckpointHandler(prefix, net, trainer) as h:
            est.fit(...)   # or a manual loop polling h.stop_training

    ``keep``: checkpoint generations retained (default
    ``MXTPU_CKPT_KEEP``).  Each save STAGES the new
    ``-preempt.params``/``.states`` pair to ``.staging`` names first
    (atomic writes + CRC32 manifests), then commits: rotate the previous
    pair to ``.1``, ``.2``, … (logrotate-style, manifests travel along)
    and rename the staged files into place.  The fallible write phase —
    including every ``retry`` re-attempt — therefore never touches the
    previous good generations; a save that dies inside the grace window
    can never destroy them or re-rotate the history.  Restore through
    :func:`restore_latest`, which verifies and falls back past damaged
    generations (docs/guardian.md).
    """

    def __init__(self, model_prefix, net, trainer=None,
                 signals=(signal.SIGTERM,), retry=None, keep=None):
        self._prefix = model_prefix
        self._net = net
        self._trainer = trainer
        self._keep = keep
        self.stop_training = False  # polled by estimator.fit
        install(self._save, signals, retry=retry)

    def _save(self):
        from .resilience import checkpoint as _ckpt
        pfile = "%s-preempt.params" % self._prefix
        sfile = "%s-preempt.states" % self._prefix
        # STAGE first, commit after: the writes (the part that can fail,
        # and the part a RetryPolicy re-runs) target staging names, so a
        # failed or retried attempt never touches the previous good
        # generations — rotating up front would let each retry re-rotate,
        # eating the history off the keep-K end and pairing params with
        # states from different save events.  The commit phase is pure
        # renames, entered only once both files exist.
        # Both files carry one shared save-event token in their
        # manifests: the two commits below are separate renames, so a
        # crash between them pairs params and states from DIFFERENT
        # saves — each individually CRC-clean.  restore_latest matches
        # by token, so a torn pair is detected and the previous
        # consistent pair loads instead.
        import os
        token = os.urandom(8).hex()
        self._net.save_parameters(pfile + ".staging")
        _ckpt.stamp_save_event(pfile + ".staging", token)
        if self._trainer is not None:
            self._trainer.save_states(sfile + ".staging")
            _ckpt.stamp_save_event(sfile + ".staging", token)
        _ckpt.rotate_history(pfile, keep=self._keep)
        _ckpt.move_with_manifest(pfile + ".staging", pfile)
        if self._trainer is not None:
            _ckpt.rotate_history(sfile, keep=self._keep)
            _ckpt.move_with_manifest(sfile + ".staging", sfile)

    def batch_end(self, estimator, *args, **kwargs):
        if preempted():
            self.stop_training = True

    def train_end(self, estimator, *args, **kwargs):
        uninstall()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        uninstall()
        return False
