"""Preemption-signal checkpointing (SURVEY §5 failure-detection row: the
reference has no elastic recovery — checkpoint-restart is the story, and
the TPU build adds the missing piece: a SIGTERM hook that saves state
before the host is reclaimed).

TPU VMs (and most batch schedulers) deliver SIGTERM with a grace window
before preemption.  ``install()`` registers a handler that (a) marks the
flag so training loops can drain cleanly via ``preempted()``, and
(b) runs the supplied save callback once, immediately, in the main
thread (Python signal handlers execute between bytecodes — jax arrays
are immutable values, so saving mid-step reads a consistent snapshot).
"""

from __future__ import annotations

import logging
import signal
import threading

from .resilience.faults import inject as _inject

__all__ = ["install", "uninstall", "preempted", "reset",
           "PreemptionCheckpointHandler"]

_lock = threading.Lock()
_state = {"flag": False, "save_fn": None, "prev": {}, "signals": ()}


def _handler(signum, frame):
    # NO lock here: signal handlers run in the main thread between
    # bytecodes, and the main thread may already hold _lock inside
    # install()/uninstall() — acquiring it would self-deadlock exactly
    # when the grace window matters.  Plain dict reads/writes are atomic
    # under the GIL, which is all the consistency this needs.
    already = _state["flag"]
    _state["flag"] = True
    save_fn = _state["save_fn"]
    if already:
        return
    logging.warning("preemption signal %s received — checkpointing",
                    signal.Signals(signum).name)
    if save_fn is not None:
        try:
            save_fn()
        except Exception:
            logging.exception("preemption checkpoint failed")


def _wrap_save(save_fn, retry):
    """Route the save through the ``checkpoint.save`` fault-injection
    site and (optionally) a RetryPolicy — a flaky checkpoint target
    inside the SIGTERM grace window is exactly when a bounded retry
    earns its keep."""
    def attempt():
        _inject("checkpoint.save")
        return save_fn()

    if retry is None:
        return attempt
    return lambda: retry.call(attempt)


def install(save_fn, signals=(signal.SIGTERM,), retry=None):
    """Install the preemption hook.  save_fn() is called once on the
    first signal; training loops may also poll preempted().  ``retry``:
    optional :class:`mxtpu.resilience.RetryPolicy` applied to the save
    (transient checkpoint-write failures re-attempt inside the grace
    window; exhaustion is logged, never propagated out of the signal
    handler)."""
    with _lock:
        uninstall_locked()
        _state["save_fn"] = _wrap_save(save_fn, retry)
        _state["signals"] = tuple(signals)
        _state["flag"] = False
        for sig in signals:
            _state["prev"][sig] = signal.signal(sig, _handler)


def uninstall_locked():
    for sig, prev in _state["prev"].items():
        try:
            signal.signal(sig, prev)
        except (ValueError, OSError):
            pass
    _state["prev"] = {}
    _state["save_fn"] = None


def uninstall():
    with _lock:
        uninstall_locked()


def preempted() -> bool:
    return _state["flag"]


def reset():
    with _lock:
        _state["flag"] = False


class PreemptionCheckpointHandler:
    """Estimator event handler: saves parameters + trainer states on
    preemption and stops the fit loop at the next batch boundary
    (plugs into gluon.contrib.estimator alongside CheckpointHandler).

    Also a context manager: ``__exit__`` always uninstalls the SIGTERM
    hook, so an exception inside the fit loop cannot leak the handler
    into unrelated later code (the event-handler API — ``batch_end`` /
    ``train_end`` — keeps working unchanged)::

        with PreemptionCheckpointHandler(prefix, net, trainer) as h:
            est.fit(...)   # or a manual loop polling h.stop_training
    """

    def __init__(self, model_prefix, net, trainer=None,
                 signals=(signal.SIGTERM,), retry=None):
        self._prefix = model_prefix
        self._net = net
        self._trainer = trainer
        self.stop_training = False  # polled by estimator.fit
        install(self._save, signals, retry=retry)

    def _save(self):
        self._net.save_parameters("%s-preempt.params" % self._prefix)
        if self._trainer is not None:
            self._trainer.save_states("%s-preempt.states" % self._prefix)

    def batch_end(self, estimator, *args, **kwargs):
        if preempted():
            self.stop_training = True

    def train_end(self, estimator, *args, **kwargs):
        uninstall()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        uninstall()
        return False
