"""Evaluation metrics (parity: python/mxnet/metric.py).

Metrics are host-side accumulators updated on (labels, preds) NDArray lists,
matching the reference's EvalMetric protocol (update / update_dict / get /
get_name_value / reset).  Array math runs through numpy after a single device
fetch per batch — the reference likewise computes metrics on CPU.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as onp

from .base import MXTPUError

__all__ = [
    "EvalMetric", "CompositeEvalMetric", "register", "create", "np",
    "Accuracy", "TopKAccuracy", "F1", "MCC", "Perplexity",
    "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
    "PearsonCorrelation", "Loss", "Torch", "Caffe", "CustomMetric",
]

_METRIC_REGISTRY: Dict[str, type] = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def _as_numpy(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return onp.asarray(x)


def create(metric, *args, **kwargs):
    """Parity: mx.metric.create — accepts name, callable, instance, or list."""
    if callable(metric) and not isinstance(metric, type):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, CompositeEvalMetric):
        return metric
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        try:
            return _METRIC_REGISTRY[metric.lower()](*args, **kwargs)
        except KeyError:
            raise MXTPUError(f"unknown metric {metric!r}") from None
    raise MXTPUError(f"cannot create metric from {metric!r}")


class EvalMetric:
    """Base metric (parity: mx.metric.EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._has_global_stats = kwargs.pop("has_global_stats", False)
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            "metric": self.__class__.__name__,
            "name": self.name,
            "output_names": self.output_names,
            "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self._has_global_stats:
            if self.global_num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.global_sum_metric / self.global_num_inst)
        return self.get()

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_global_name_value(self):
        if self._has_global_stats:
            name, value = self.get_global()
            if not isinstance(name, list):
                name = [name]
            if not isinstance(value, list):
                value = [value]
            return list(zip(name, value))
        return self.get_name_value()

    def _update(self, metric, inst):
        self.sum_metric += metric
        self.num_inst += inst
        self.global_sum_metric += metric
        self.global_num_inst += inst


def check_label_shapes(labels, preds, shape=False):
    """Parity: mx.metric.check_label_shapes."""
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            f"Shape of labels {label_shape} does not match shape of "
            f"predictions {pred_shape}")


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (parity: CompositeEvalMetric)."""

    def __init__(self, metrics=None, name="composite",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError(f"Metric index {index} is out of range 0 and "
                              f"{len(self.metrics)}")

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def reset_local(self):
        try:
            for metric in self.metrics:
                metric.reset_local()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)

    def get_global(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get_global()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register
class Accuracy(EvalMetric):
    """Classification accuracy (parity: mx.metric.Accuracy)."""

    def __init__(self, axis=1, name="accuracy",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         axis=axis, has_global_stats=True)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred_label = _as_numpy(pred_label)
            label = _as_numpy(label)
            if pred_label.ndim > label.ndim:
                pred_label = onp.argmax(pred_label, axis=self.axis)
            pred_label = pred_label.astype(onp.int32).ravel()
            label = label.astype(onp.int32).ravel()
            check_label_shapes(label, pred_label, shape=True)
            correct = (pred_label == label).sum()
            self._update(float(correct), len(pred_label))


@register
class TopKAccuracy(EvalMetric):
    """Top-k accuracy (parity: TopKAccuracy)."""

    def __init__(self, top_k=1, name="top_k_accuracy",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         top_k=top_k, has_global_stats=True)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            pred_label = _as_numpy(pred_label).astype(onp.float32)
            label = _as_numpy(label).astype(onp.int32)
            assert pred_label.ndim == 2, "Predictions should be 2 dims"
            pred_label = onp.argsort(pred_label, axis=1)
            num_samples = pred_label.shape[0]
            num_dims = pred_label.shape[1]
            if num_dims == 1:
                self._update(float((pred_label.ravel() == label.ravel()).sum()),
                             num_samples)
            else:
                num_classes = pred_label.shape[1]
                top_k = min(num_classes, self.top_k)
                correct = 0.0
                for j in range(top_k):
                    correct += float(
                        (pred_label[:, num_classes - 1 - j].ravel()
                         == label.ravel()).sum())
                self._update(correct, num_samples)


class _BinaryClassificationMetrics:
    """Confusion-matrix accumulator shared by F1/MCC (parity: same helper)."""

    def __init__(self):
        self.reset_stats()

    def update_binary_stats(self, label, pred):
        pred_label = onp.argmax(pred, axis=1)
        check_label_shapes(label, pred)
        if len(onp.unique(label)) > 2:
            raise ValueError("%s currently only supports binary "
                             "classification." % type(self).__name__)
        pred_true = (pred_label == 1)
        pred_false = 1 - pred_true
        label_true = (label == 1)
        label_false = 1 - label_true
        true_pos = (pred_true * label_true).sum()
        false_pos = (pred_true * label_false).sum()
        false_neg = (pred_false * label_true).sum()
        true_neg = (pred_false * label_false).sum()
        self.true_positives += true_pos
        self.false_positives += false_pos
        self.false_negatives += false_neg
        self.true_negatives += true_neg

    @property
    def precision(self):
        if self.true_positives + self.false_positives > 0:
            return float(self.true_positives) / (
                self.true_positives + self.false_positives)
        return 0.0

    @property
    def recall(self):
        if self.true_positives + self.false_negatives > 0:
            return float(self.true_positives) / (
                self.true_positives + self.false_negatives)
        return 0.0

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return 2 * self.precision * self.recall / (
                self.precision + self.recall)
        return 0.0

    @property
    def matthewscc(self):
        if not self.total_examples:
            return 0.0
        true_pos = float(self.true_positives)
        false_pos = float(self.false_positives)
        false_neg = float(self.false_negatives)
        true_neg = float(self.true_negatives)
        terms = [(true_pos + false_pos), (true_pos + false_neg),
                 (true_neg + false_pos), (true_neg + false_neg)]
        denom = 1.0
        for t in filter(lambda t: t != 0.0, terms):
            denom *= t
        return ((true_pos * true_neg) - (false_pos * false_neg)) / math.sqrt(
            denom)

    @property
    def total_examples(self):
        return (self.false_negatives + self.false_positives
                + self.true_negatives + self.true_positives)

    def reset_stats(self):
        self.false_positives = 0
        self.false_negatives = 0
        self.true_positives = 0
        self.true_negatives = 0


@register
class F1(EvalMetric):
    """Binary F1 (parity: mx.metric.F1; average in {'macro','micro'})."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        super().__init__(name, output_names, label_names,
                         has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(_as_numpy(label), _as_numpy(pred))
        if self.average == "macro":
            self._update(self.metrics.fscore, 1)
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * self.metrics.total_examples
            self.global_sum_metric = self.sum_metric
            self.num_inst = self.metrics.total_examples
            self.global_num_inst = self.num_inst

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0
        self.global_sum_metric = 0.0
        self.global_num_inst = 0
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (parity: mx.metric.MCC)."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        self._average = average
        self._metrics = _BinaryClassificationMetrics()
        super().__init__(name, output_names, label_names,
                         has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        for label, pred in zip(labels, preds):
            self._metrics.update_binary_stats(_as_numpy(label),
                                              _as_numpy(pred))
        if self._average == "macro":
            self._update(self._metrics.matthewscc, 1)
            self._metrics.reset_stats()
        else:
            self.sum_metric = (self._metrics.matthewscc
                               * self._metrics.total_examples)
            self.global_sum_metric = self.sum_metric
            self.num_inst = self._metrics.total_examples
            self.global_num_inst = self.num_inst

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0.0
        self.global_sum_metric = 0.0
        self.global_num_inst = 0.0
        if hasattr(self, "_metrics"):
            self._metrics.reset_stats()


@register
class Perplexity(EvalMetric):
    """Perplexity (parity: mx.metric.Perplexity)."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis,
                         has_global_stats=True)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype(onp.int64)
            pred = _as_numpy(pred)
            flat_label = label.ravel()
            probs = pred.reshape(-1, pred.shape[-1])[
                onp.arange(flat_label.size), flat_label]
            if self.ignore_label is not None:
                ignore = (flat_label == self.ignore_label)
                probs = onp.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(onp.sum(onp.log(onp.maximum(1e-10, probs))))
            num += flat_label.size
        self._update(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.global_sum_metric
                                    / self.global_num_inst))


@register
class MAE(EvalMetric):
    """Mean absolute error (parity: mx.metric.MAE)."""

    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._update(float(onp.abs(label - pred).mean()), 1)


@register
class MSE(EvalMetric):
    """Mean squared error (parity: mx.metric.MSE)."""

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._update(float(((label - pred) ** 2.0).mean()), 1)


@register
class RMSE(EvalMetric):
    """Root mean squared error (parity: mx.metric.RMSE)."""

    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._update(float(onp.sqrt(((label - pred) ** 2.0).mean())), 1)


@register
class CrossEntropy(EvalMetric):
    """Cross entropy over class probabilities (parity: CrossEntropy)."""

    def __init__(self, eps=1e-12, name="cross-entropy",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, eps=eps,
                         has_global_stats=True)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[onp.arange(label.shape[0]), label.astype(onp.int64)]
            cross_entropy = (-onp.log(prob + self.eps)).sum()
            self._update(float(cross_entropy), label.shape[0])


@register
class NegativeLogLikelihood(EvalMetric):
    """NLL (parity: NegativeLogLikelihood)."""

    def __init__(self, eps=1e-12, name="nll-loss",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, eps=eps,
                         has_global_stats=True)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            num_examples = pred.shape[0]
            assert label.shape[0] == num_examples
            prob = pred[onp.arange(num_examples), label.astype(onp.int64)]
            nll = (-onp.log(prob + self.eps)).sum()
            self._update(float(nll), num_examples)


@register
class PearsonCorrelation(EvalMetric):
    """Pearson correlation (parity: PearsonCorrelation)."""

    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         has_global_stats=True)

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            self._update(float(onp.corrcoef(label, pred)[0, 1]), 1)


@register
class Loss(EvalMetric):
    """Mean of a loss output (parity: mx.metric.Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         has_global_stats=True)

    def update(self, _, preds):
        preds = _tolist(preds)
        for pred in preds:
            pred = _as_numpy(pred)
            loss = float(pred.sum())
            self._update(loss, pred.size)


@register
class Torch(Loss):
    """Legacy alias (parity: mx.metric.Torch)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    """Legacy alias (parity: mx.metric.Caffe)."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap a feval(label, pred) function (parity: CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names,
                         feval=feval, allow_extra_outputs=allow_extra_outputs,
                         has_global_stats=True)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        labels, preds = _tolist(labels), _tolist(preds)
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self._update(sum_metric, num_inst)
            else:
                self._update(reval, 1)

    def get_config(self):
        raise NotImplementedError("CustomMetric cannot be serialized")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Parity: mx.metric.np — make a CustomMetric from a numpy feval."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def _tolist(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


# short aliases (parity: the reference registers these names too)
_METRIC_ALIASES = {
    "acc": "accuracy",
    "ce": "crossentropy",
    "nll_loss": "negativeloglikelihood",
    "top_k_accuracy": "topkaccuracy",
    "top_k_acc": "topkaccuracy",
    "pearsonr": "pearsoncorrelation",
}
for _alias, _target in _METRIC_ALIASES.items():
    if _target in _METRIC_REGISTRY and _alias not in _METRIC_REGISTRY:
        _METRIC_REGISTRY[_alias] = _METRIC_REGISTRY[_target]
