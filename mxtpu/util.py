"""Misc utilities (parity: python/mxnet/util.py).

The reference's np_shape/np_array semantics flags control NumPy-compatible
behavior; mxtpu is NumPy-shaped by construction (zero-size dims and scalar
arrays are native to jax), so the flags are accepted and always-on.
"""

from __future__ import annotations

import functools
import inspect
import os

__all__ = ["makedirs", "get_gpu_count", "get_gpu_memory",
           "is_np_shape", "is_np_array", "set_np", "reset_np", "use_np",
           "np_shape", "np_array", "use_np_shape", "use_np_array",
           "getenv", "setenv", "default_array"]


def makedirs(d):
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def get_gpu_count():
    from .context import num_tpus
    return num_tpus()


def get_gpu_memory(gpu_dev_id=0):
    from .context import _accel_devices
    devs = _accel_devices()  # process-local, matching Context ids
    if gpu_dev_id >= len(devs):
        raise ValueError("invalid device id")
    stats = devs[gpu_dev_id].memory_stats() or {}
    return (stats.get("bytes_in_use", 0), stats.get("bytes_limit", 0))


# -- numpy semantics flags: always on (documented divergence: there is no
#    legacy MXNet shape semantics to switch back to) ------------------------

def is_np_shape():
    return True


def is_np_array():
    return True


def set_np(shape=True, array=True, dtype=False):
    if not shape or not array:
        raise ValueError(
            "mxtpu is NumPy-semantics-native; legacy shape semantics "
            "cannot be enabled (documented divergence)")


def reset_np():
    pass


class _NoopScope:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def np_shape(active=True):
    return _NoopScope()


def np_array(active=True):
    return _NoopScope()


def use_np_shape(func):
    return func


def use_np_array(func):
    return func


def use_np(func):
    return func


def getenv(name):
    return os.environ.get(name)


def setenv(name, value):
    os.environ[name] = value


def default_array(source_array, ctx=None, dtype=None):
    from . import ndarray as nd
    return nd.array(source_array, ctx=ctx, dtype=dtype)
